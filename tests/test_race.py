"""gomerace dynamic prong: the lockset detector (analysis.racecheck),
the deterministic interleaving driver (analysis.interleave), and the
seeded regression for the double-start lifecycle race the round fixed.

The injected-race goldens mirror the three classic shapes the detector
must catch — unguarded counter, check-then-act, publish-without-lock —
plus their properly-locked twins, which must stay silent. The disabled
path is held to the same zero-allocation contract as the tracer,
compile journal, and fault registry.
"""

from __future__ import annotations

import sys
import threading

import pytest

from gome_tpu.analysis.interleave import (
    Interleaver,
    SteppingEvent,
    SteppingLock,
)
from gome_tpu.analysis.racecheck import (
    RACECHECK,
    RaceCheck,
    TrackedLock,
    watch,
)


@pytest.fixture(autouse=True)
def _fresh_detector():
    """Each test gets a clean process-wide detector and leaves it
    disabled (other tests rely on the zero-cost disabled path)."""
    RACECHECK.reset()
    yield
    RACECHECK.disable()
    RACECHECK.reset()


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump_unlocked(self):
        self.n = self.n + 1

    def bump_locked(self):
        with self._lock:
            self.n = self.n + 1


# -- interleaving driver ----------------------------------------------------


def test_interleaver_same_seed_same_trace():
    def make_worker(log, me):
        def worker(step):
            for _ in range(5):
                log.append(me)
                step()

        return worker

    runs = []
    for _ in range(2):
        log: list[str] = []
        il = Interleaver(seed=42)
        trace = il.run(make_worker(log, "a"), make_worker(log, "b"))
        runs.append((trace, log))
    assert runs[0] == runs[1]
    # Both workers actually ran to completion.
    assert runs[0][1].count("a") == 5 and runs[0][1].count("b") == 5


def test_interleaver_seeds_explore_distinct_schedules():
    def worker(step):
        for _ in range(6):
            step()

    traces = set()
    for seed in range(8):
        il = Interleaver(seed=seed)
        traces.add(tuple(il.run(worker, worker)))
    assert len(traces) > 1


def test_interleaver_collects_worker_exceptions():
    def ok(step):
        return "fine"

    def boom(step):
        raise ValueError("expected")

    il = Interleaver(seed=0)
    il.run(ok, boom)
    assert il.results[0] == "fine"
    assert isinstance(il.errors[1], ValueError)


def test_stepping_lock_schedules_through_contention():
    """A worker blocked on a SteppingLock yields instead of wedging the
    cooperative scheduler: both critical sections complete, mutually
    excluded, on every seed."""
    for seed in range(6):
        il = Interleaver(seed=seed)
        lock = SteppingLock(il.step)
        inside = []

        def worker(step, lock=lock, inside=inside):
            with lock:
                inside.append("enter")
                step()  # deschedule while HOLDING the lock
                inside.append("exit")

        il.run(worker, worker)
        assert inside == ["enter", "exit", "enter", "exit"]


# -- injected-race goldens --------------------------------------------------


def _hammer(fn, n_threads=2, iters=200):
    """Free-running (non-interleaved) concurrent driver: the detector
    must catch discipline violations without a cooperative schedule.
    The barrier keeps all workers alive simultaneously — a worker that
    finished before the next one spawned could hand its (OS-reused)
    thread ident to the successor, and same-ident accesses never look
    shared to the detector."""
    barrier = threading.Barrier(n_threads)

    def run():
        barrier.wait()
        for _ in range(iters):
            fn()

    threads = [
        threading.Thread(target=run) for _ in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_unguarded_counter_is_reported():
    c = watch(Counter(), ("n",), label="UnguardedCounter")
    RACECHECK.enable()
    _hammer(c.bump_unlocked)
    RACECHECK.disable()
    reports = RACECHECK.reports()
    # The read and the write of `self.n = self.n + 1` share a source
    # line, so the dedup fingerprint collapses them into one report —
    # whichever side fired first.
    assert any(
        r.label == "UnguardedCounter" and r.attr == "n" for r in reports
    )
    # Both sides of the race are in the report.
    r = reports[0]
    assert r.site_here and r.site_prev
    assert any("bump_unlocked" in f for f in r.site_here)


def test_locked_counter_is_silent():
    c = watch(Counter(), ("n",), label="LockedCounter")
    RACECHECK.enable()
    _hammer(c.bump_locked)
    RACECHECK.disable()
    assert RACECHECK.reports() == []
    assert c.n == 400  # TrackedLock still mutually excludes


def test_publish_without_lock_is_reported():
    """One side writes under the lock, the other publishes bare: the
    candidate lockset empties and the inconsistency is reported even
    though *most* accesses were disciplined."""
    c = watch(Counter(), ("n",), label="MixedCounter")
    RACECHECK.enable()
    t = threading.Thread(
        target=lambda: [c.bump_locked() for _ in range(200)]
    )
    t.start()
    for _ in range(200):
        c.bump_unlocked()
    t.join()
    RACECHECK.disable()
    assert any(
        r.label == "MixedCounter" and r.attr == "n"
        for r in RACECHECK.reports()
    )


def test_check_then_act_is_reported_and_loses_update():
    """The classic window: `if slot is None: slot = me` with a forced
    deschedule between check and act. The interleaver proves the lost
    update (both workers observe None) and the detector reports the
    unguarded write."""

    class Holder:
        def __init__(self):
            self.slot = None

    RACECHECK.enable()
    lost_update_seeds = []
    for seed in range(16):
        h = watch(Holder(), ("slot",), lock_attrs=(), label="Holder")
        il = Interleaver(seed=seed)
        winners = []

        def claim(step, me, h=h, winners=winners):
            if h.slot is None:
                step()  # the race window
                h.slot = me
                winners.append(me)

        il.run(
            lambda step: claim(step, "a"), lambda step: claim(step, "b")
        )
        if len(winners) == 2:  # both passed the check: lost update
            lost_update_seeds.append(seed)
    RACECHECK.disable()
    # The seed sweep deterministically finds schedules that lose the
    # update, and the detector reported the unguarded write.
    assert lost_update_seeds
    assert any(r.attr == "slot" for r in RACECHECK.reports())


def test_reports_dedupe_and_suppress():
    c = watch(Counter(), ("n",), label="DedupeCounter")
    RACECHECK.enable()
    _hammer(c.bump_unlocked, iters=500)
    RACECHECK.disable()
    reports = RACECHECK.reports()
    fingerprints = [r.fingerprint for r in reports]
    assert len(fingerprints) == len(set(fingerprints))
    for r in reports:
        RACECHECK.suppress(r.fingerprint)
    assert RACECHECK.reports() == []
    assert RACECHECK.reports(include_suppressed=True) == reports
    # label.attr suppression works too
    RACECHECK.reset()
    RACECHECK.enable()
    _hammer(c.bump_unlocked, iters=500)
    RACECHECK.disable()
    assert RACECHECK.reports()
    RACECHECK.suppress("DedupeCounter.n")
    assert RACECHECK.reports() == []


def test_exclusive_then_read_only_sharing_is_silent():
    """Init-then-publish: one thread initializes bare, others only read.
    The Eraser EXCLUSIVE->SHARED refinement must not report it."""

    class Config:
        def __init__(self):
            self.value = 0

    cfg = watch(Config(), ("value",), lock_attrs=(), label="Config")
    RACECHECK.enable()
    cfg.value = 7  # main thread, exclusive
    seen = []
    _hammer(lambda: seen.append(cfg.value), n_threads=3, iters=50)
    RACECHECK.disable()
    assert RACECHECK.reports() == []
    assert set(seen) == {7}


# -- disabled-path contract -------------------------------------------------


@pytest.mark.skipif(
    not hasattr(sys, "getallocatedblocks"),
    reason="CPython-only allocation accounting",
)
def test_disabled_path_is_zero_alloc():
    """Disabled note_access is one attribute check and zero allocations
    (the TRACER/JOURNAL/FAULTS contract)."""
    note = RACECHECK.note_access

    def drill(n):
        i = 0
        while i < n:
            note("Warm", "attr", True)
            i += 1

    drill(64)  # warm lazy caches
    before = sys.getallocatedblocks()
    drill(1000)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"disabled note_access allocated {after - before}"


def test_tracked_lock_plain_when_disabled():
    lock = TrackedLock()
    with lock:
        assert lock.held_by_me()
    assert not lock.locked()
    assert RACECHECK._held_stack() == []


# -- service integration ----------------------------------------------------


def test_maybe_arm_is_env_gated(monkeypatch):
    from gome_tpu.analysis.racecheck import maybe_arm

    monkeypatch.delenv("GOME_RACECHECK", raising=False)
    assert maybe_arm(object()) is False
    assert RACECHECK.enabled is False


def test_arm_service_watches_feed_and_consumer():
    from gome_tpu.analysis.racecheck import arm_service
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.service.matchfeed import MatchFeed

    class FakeSvc:
        pass

    svc = FakeSvc()
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    svc.feed = MatchFeed(bus, log_events=False)
    watched = arm_service(svc)
    assert svc.feed in watched and svc.feed.seq in watched
    # The feed's own locks became tracked, its counters became watched
    # properties, and the feed still works.
    assert isinstance(svc.feed._lock, TrackedLock)
    RACECHECK.enable()
    assert svc.feed.run_once() == 0
    RACECHECK.disable()


# -- the double-start lifecycle race (fixed this round) ---------------------


def _double_start(seed: int):
    """Two workers race MatchFeed.start() under one seeded schedule,
    with the exact pre-fix window — the `_stop.clear()` between the
    already-started check and the thread assignment — turned into a
    schedule point."""
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.service.matchfeed import MatchFeed

    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    feed = MatchFeed(bus, log_events=False)
    il = Interleaver(seed=seed)
    # _life must step (a worker holding it descheduled mid-start would
    # otherwise wedge the schedule); _stop.clear() IS the race window.
    feed._life = SteppingLock(il.step)
    feed._stop = SteppingEvent(il.step)
    il.run(lambda step: feed.start(), lambda step: feed.start())
    try:
        live = [
            t for t in threading.enumerate() if t.name == "match-feed"
        ]
        return il, live
    finally:
        feed.stop()


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 7, 1234])
def test_matchfeed_double_start_is_serialized(seed):
    """Regression for the watchdog-vs-operator double start: before the
    _life lock, a schedule that deschedules worker A between the
    `_thread is None` check and the assignment let both workers spawn a
    fan-out loop (double delivery, lost join). Post-fix, EVERY seeded
    schedule yields exactly one winner, one RuntimeError loser, one
    live feed thread."""
    il, live = _double_start(seed)
    errors = [e for e in il.errors if e is not None]
    assert len(errors) == 1, f"trace {il.trace}: errors {il.errors}"
    assert isinstance(errors[0], RuntimeError)
    assert len(live) == 1, f"trace {il.trace}: {live}"


def test_consumer_double_start_is_serialized():
    """Same lifecycle contract on the order consumer (its start() got
    the same _life serialization this round)."""
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    engine = MatchEngine(
        config=BookConfig(cap=16, max_fills=4), n_slots=16, max_t=4
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(engine, bus, batch_n=16, batch_wait_s=0)
    il = Interleaver(seed=5)
    consumer._life = SteppingLock(il.step)
    il.run(
        lambda step: consumer.start(), lambda step: consumer.start()
    )
    try:
        errors = [e for e in il.errors if e is not None]
        assert len(errors) == 1 and isinstance(errors[0], RuntimeError)
        live = [
            t
            for t in threading.enumerate()
            if t.name == "order-consumer"
        ]
        assert len(live) == 1
    finally:
        consumer.stop()


def test_private_detector_instances_are_independent():
    """Tests may build private RaceCheck instances without touching the
    process-wide singleton's state."""
    rc = RaceCheck()
    rc.enable()
    rc.note_access("X", "y", True)
    assert RACECHECK._vars == {}
    rc.disable()

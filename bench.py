"""Benchmark: sustained device matching throughput, 10K-symbol exchange-scale
load on one TPU chip (BASELINE.json config 4 shape; north star >= 1M
orders/sec across 10K symbols on one v5e).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "orders/sec", "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.json "published":
{}), so the denominator is the north-star target itself — vs_baseline =
value / 1e6, i.e. the fraction of the 1M orders/sec goal achieved.

Method: S symbol lanes x T time slots of real limit orders (tight price
band around mid so flows cross and match constantly), packed host-side with
numpy, executed as G chained batch_step calls (scan over T x vmap over S)
with donated book state. Synchronization discipline: the device runs the
G-grid chain without ANY host round trip; each grid's StepOutput is folded
into a device-side scalar accumulator (total fills + total overflows), and
ONE data-dependent scalar fetch closes the timed region. This matters
doubly on a tunneled TPU (host<->device round trips cost ~0.1-1s flat), and
it is also the production shape: the consumer keeps the device fed and
decodes event batches asynchronously, off the critical path. Orders/sec
counts every op applied to a book. Run `python bench.py --check` for a tiny
self-check on any platform.

Dtype note: the default is BENCH_DTYPE=int32 + the VMEM-resident Pallas
kernel — the high-throughput configuration, valid for workloads whose
tick/lot ranges keep per-side depth prefix sums under 2^31 (the bench's
int32 grids use coarser lot units accordingly). BENCH_DTYPE=int64 selects
the exact-integer envelope of the reference's accuracy=8 fixed-point
scaling (SURVEY §2.2) — prefix sums over a full (default 256-slot) side
can exceed 2^31 at 1e8-scaled lots — and runs on the scan path (Mosaic has
no 64-bit lowering).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np


def build_grids(s, t, g, seed=0, dtype=np.int64):
    """G full [S, T] grids of crossing limit-order flow around mid=1.00
    (1e8 ticks at accuracy 8): uniform prices in ±0.5% of mid, volumes
    1..100 lots-of-1e6, random sides. Every slot is a live order."""
    rng = np.random.default_rng(seed)
    grids = []
    oid_base = 1
    for _ in range(g):
        price = rng.integers(99_500_000, 100_500_000, size=(s, t), dtype=dtype)
        volume = rng.integers(1, 101, size=(s, t), dtype=dtype) * 1_000_000
        side = rng.integers(0, 2, size=(s, t), dtype=np.int32)
        action = np.ones((s, t), np.int32)
        oid = (np.arange(s * t, dtype=dtype) + oid_base).reshape(s, t)
        oid_base += s * t
        uid = np.ones((s, t), dtype=dtype)
        grids.append(
            dict(
                action=action, side=side,
                is_market=np.zeros((s, t), np.int32),
                price=price, volume=volume, oid=oid, uid=uid,
            )
        )
    return grids


def build_config_grids(cfg, s, t, g, seed=0, dtype=np.int64):
    """BASELINE.json config shapes 1-5 (BENCH_CONFIG); grids are NOP-padded
    where a shape leaves slots idle (the caller counts action != 0 for its
    throughput denominator). The default bench path is build_grids: uniform
    full grids, the exchange-scale config-4 shape at peak device utilization.

      1  single-symbol limit cross (BUY sweeps resting asks; S=1 lane live)
      2  single-symbol mixed stream with partial fills + cancels
      3  100-symbol Poisson flow (only lanes 0..99 live, Poisson thinning)
      4  Zipf-skewed per-symbol arrival rates across all S lanes
      5  market + limit mix with multi-level depth-walk fills
    """
    rng = np.random.default_rng(seed)
    grids = []
    oid_base = 1
    for _ in range(g):
        d = dict(
            action=np.zeros((s, t), np.int32),
            side=np.zeros((s, t), np.int32),
            is_market=np.zeros((s, t), np.int32),
            price=np.zeros((s, t), dtype),
            volume=np.zeros((s, t), dtype),
            oid=np.zeros((s, t), dtype),
            uid=np.ones((s, t), dtype),
        )
        if cfg in (1, 2):
            mask = np.zeros((s, t), bool)
            mask[0, :] = True
        elif cfg == 3:
            lanes = min(100, s)
            mask = np.zeros((s, t), bool)
            mask[:lanes] = rng.random((lanes, t)) < 0.7  # Poisson thinning
        elif cfg == 4:
            ranks = np.arange(1, s + 1, dtype=np.float64)
            p_live = np.minimum(1.0, (1.0 / ranks) * 8)  # Zipf(1) rates
            mask = rng.random((s, t)) < p_live[:, None]
        else:  # 5
            mask = np.ones((s, t), bool)
        n = int(mask.sum())
        d["action"][mask] = 1
        d["side"][mask] = rng.integers(0, 2, n)
        if cfg == 1:
            # alternate resting asks and sweeping bids on the one lane
            tt = np.arange(t)
            d["side"][0] = (tt % 2 == 0).astype(np.int32)  # even: SALE rests
            d["price"][0] = np.where(
                tt % 2 == 0, 100_000_000 + (tt % 8) * 1000, 101_000_000
            )
            # Balanced flow: each sweeping bid consumes exactly the two
            # asks rested since the last one (5+5 lots) — the book hovers
            # at steady depth instead of accumulating a side without bound.
            d["volume"][0] = np.where(tt % 2 == 0, 5_000_000, 10_000_000)
        else:
            d["price"][mask] = rng.integers(99_500_000, 100_500_000, n)
            d["volume"][mask] = rng.integers(1, 101, n) * 1_000_000
        if cfg in (2, 5):
            # ~15% cancels of random earlier oids (misses allowed — the
            # reference's DeleteOrder on a filled order returns false)
            cm = mask & (rng.random((s, t)) < 0.15)
            d["action"][cm] = 2
            d["oid"][cm] = rng.integers(1, max(oid_base, 2), int(cm.sum()))
        if cfg == 5:
            mm = mask & (rng.random((s, t)) < 0.25) & (d["action"] == 1)
            d["is_market"][mm] = 1
        fresh = d["action"] == 1
        d["oid"][fresh] = oid_base + np.arange(int(fresh.sum()))
        oid_base += int(fresh.sum())
        grids.append(d)
    return grids


def _next_pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


FIELDS = ("action", "side", "is_market", "price", "volume", "oid", "uid")


def pack_dense_rounds(grids, t_dense, s_total):
    """Convert NOP-padded [S, T] grids into dense rounds over LIVE lanes
    (the host-side packing the engine's dense path does —
    gome_tpu.engine.batch.dense_batch_step): per lane, concatenate its live
    ops across the whole timeline (FIFO preserved), then emit rounds of up
    to t_dense ops per still-live lane until every stream drains. Rows and
    time depth bucket to powers of two (bounded compile shapes); padding
    rows carry the out-of-range sentinel lane id = s_total.

    Returns a list of (lane_ids[R], ops dict of [R, T_d]) numpy rounds.
    """
    streams: dict[int, list] = {}
    for d in grids:
        live = d["action"] != 0
        for lane in np.nonzero(live.any(axis=1))[0]:
            m = live[lane]
            streams.setdefault(int(lane), []).append(
                {f: d[f][lane][m] for f in FIELDS}
            )
    merged = {
        lane: {f: np.concatenate([c[f] for c in chunks]) for f in FIELDS}
        for lane, chunks in streams.items()
    }
    offsets = {lane: 0 for lane in merged}
    rounds = []

    def emit(lanes, depth):
        # A round touching most lanes goes out as a FULL grid (lane_ids
        # None): a gather/scatter of nearly every row costs one DMA per row
        # on TPU — at 8K rows that dwarfs the matching work itself.
        if len(lanes) > s_total // 2:
            ops = {
                f: np.zeros(
                    (s_total, depth),
                    np.int32 if f in ("action", "side", "is_market")
                    else merged[lanes[0]][f].dtype,
                )
                for f in FIELDS
            }
            for lane in sorted(lanes):
                s0 = offsets[lane]
                chunk = {
                    f: merged[lane][f][s0 : s0 + depth] for f in FIELDS
                }
                n = len(chunk["action"])
                for f in FIELDS:
                    ops[f][lane, :n] = chunk[f]
                offsets[lane] += n
                if offsets[lane] >= len(merged[lane]["action"]):
                    del merged[lane], offsets[lane]
            rounds.append((None, ops))
            return
        # Min 8 rows: the Pallas kernel's sublane-alignment floor; sentinel
        # padding rows are free.
        rows = max(8, _next_pow2(len(lanes)))
        ops = {
            f: np.zeros(
                (rows, depth),
                np.int32 if f in ("action", "side", "is_market")
                else merged[lanes[0]][f].dtype,
            )
            for f in FIELDS
        }
        lane_ids = np.full(rows, s_total, np.int32)
        for r, lane in enumerate(sorted(lanes)):
            lane_ids[r] = lane
            s0 = offsets[lane]
            chunk = {f: merged[lane][f][s0 : s0 + depth] for f in FIELDS}
            n = len(chunk["action"])
            for f in FIELDS:
                ops[f][r, :n] = chunk[f]
            offsets[lane] += n
            if offsets[lane] >= len(merged[lane]["action"]):
                del merged[lane], offsets[lane]
        rounds.append((lane_ids, ops))

    while merged:
        # Per-dispatch cost on a tunneled TPU is milliseconds, so FEW FAT
        # rounds beat many tight ones. Each sweep emits at most two rounds:
        # every short-stream lane in one shallow depth-8 round (padding is
        # bounded 8x, and the whole round is one dispatch), and the deep
        # lanes in one round as deep as the kernel's VMEM budget allows for
        # their block size (record outputs are [T, K, block]) — a lane
        # appears at most once per sweep, so its chunks stay FIFO.
        shallow, deep, max_deep = [], [], 0
        for lane in merged:
            rem = len(merged[lane]["action"]) - offsets[lane]
            if rem <= 8:
                shallow.append(lane)
            else:
                deep.append(lane)
                max_deep = max(max_deep, rem)
        if shallow:
            emit(shallow, 8)
        if deep:
            block = min(max(8, _next_pow2(len(deep))), 128)
            t_vmem = (64 * 128) // block  # ~6MB of [T, K, block] records
            emit(deep, min(t_dense, t_vmem, _next_pow2(max_deep)))
    return rounds


def service_main():
    """End-to-end SERVICE bench: binary ORDER frames through the real
    consumer (frame decode -> pre-pool admission -> vectorized pack ->
    device matching -> device-side event compaction -> one overlapped
    fetch -> columnar decode -> EVENT-frame publish -> offset commit).

    Prints ONE JSON line with the measured gateway->matchOrder number.
    On this dev environment the device link runs at single-digit MB/s
    (measured; a production TPU host attaches at PCIe speeds), so the
    stderr breakdown also reports the pipeline rate excluding the time
    blocked on that fetch — the number the same pipeline sustains when the
    link is not the bottleneck."""
    check = "--check" in sys.argv
    import jax

    if check:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.bus.colwire import encode_order_frame
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine import frames as engine_frames
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    N = int(os.environ.get("SVC_ORDERS", 8_192 if check else 1_048_576))
    FRAME = int(os.environ.get("SVC_FRAME", 2_048 if check else 262_144))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 10_240))
    CAP = int(os.environ.get("SVC_CAP", 32 if check else 256))
    PIPE = int(os.environ.get("SVC_PIPELINE", 2))  # cross-frame pipelining
    engine = MatchEngine(
        config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
        n_slots=S,
        max_t=32,
        kernel="pallas",
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=PIPE,
    )

    rng = np.random.default_rng(7)
    symbols = [f"sym{i}" for i in range(S)]

    def build_frame(n, oid0):
        sym_idx = rng.integers(0, S, n).astype(np.uint32)
        side = rng.integers(0, 2, n).astype(np.uint8)
        price = rng.integers(99_500_000, 100_500_000, n).astype(np.int64)
        volume = rng.integers(1, 101, n).astype(np.int64)
        oids = np.char.add(
            "o", np.arange(oid0, oid0 + n).astype("U12")
        ).astype("S")
        payload = encode_order_frame(
            n, np.ones(n, np.uint8), side, np.zeros(n, np.uint8),
            price, volume, symbols, sym_idx,
            ["u"], np.zeros(n, np.uint32), oids,
        )
        return payload, sym_idx, oids

    # Generate + gateway-mark everything off the clock (marking is the
    # gateway's job, concurrent with the consumer in a real deployment).
    pool = engine.pre_pool
    payloads = []
    oid0 = 1
    # Two warmup frames: frame geometry (grid-2 packed counts, compaction
    # pow2 classes) only stabilizes after the books reach steady state, and
    # every distinct shape is a tens-of-seconds AOT compile on the tunnel —
    # all of it must happen off the clock. Chunk by min(FRAME, N) so small
    # SVC_ORDERS runs still produce distinct warmup + timed frames.
    FRAME = min(FRAME, N)
    N_WARM = 2
    n_warm = N_WARM * FRAME
    for start in range(0, n_warm + N, FRAME):
        n = min(FRAME, n_warm + N - start)
        payload, sym_idx, oids = build_frame(n, oid0)
        oid0 += n
        payloads.append(payload)
        for k, o in zip(sym_idx.tolist(), oids.tolist()):
            pool.add((symbols[k], "u", o.decode()))

    for p in payloads[:N_WARM]:
        bus.order_queue.publish(p)
    consumer.drain()
    engine_frames.FETCH_SECONDS = 0.0

    ev_skip = bus.match_queue.end_offset()  # warmup frames' events
    for p in payloads[N_WARM:]:
        bus.order_queue.publish(p)
    t0 = time.perf_counter()
    n_done = consumer.drain()
    elapsed = time.perf_counter() - t0
    fetch_s = engine_frames.FETCH_SECONDS

    from gome_tpu.bus.colwire import decode_event_frame

    n_events = 0
    ev_bytes = 0
    for m in bus.match_queue.read_from(ev_skip, 1 << 30):
        ev_bytes += len(m.body)
        n_events += len(decode_event_frame(m.body))

    throughput = n_done / elapsed
    result = {
        "metric": (
            f"service throughput gateway->matchOrder, {S} symbols, "
            f"{FRAME}-order frames, int32 pallas, device-side event "
            "compaction"
        ),
        "value": round(throughput),
        "unit": "orders/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
    }
    print(json.dumps(result))
    host_s = max(elapsed - fetch_s, 1e-9)
    print(
        f"# orders={n_done} events={n_events} elapsed={elapsed:.3f}s "
        f"fetch_blocked={fetch_s:.3f}s (dev-tunnel link) | "
        f"pipeline-ex-fetch {n_done / host_s / 1e6:.2f}M orders/sec | "
        f"event-frame bytes/order={ev_bytes / max(n_done, 1):.1f}",
        file=sys.stderr,
    )


def main():
    if "--service" in sys.argv:
        return service_main()
    check = "--check" in sys.argv
    DTYPE = os.environ.get("BENCH_DTYPE", "int32")  # int64 | int32
    import jax

    # x64 only when the book dtype needs it: with x64 on, every jnp.arange /
    # Python-int literal inside the kernel promotes to int64, which Mosaic
    # (Pallas TPU) rejects and which doubles index-array traffic.
    if DTYPE == "int64":
        jax.config.update("jax_enable_x64", True)
    if check:
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_PLATFORM"):
        # Env JAX_PLATFORMS is consumed at interpreter start by this image's
        # sitecustomize; late override must go through jax.config.
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp

    CFG = os.environ.get("BENCH_CONFIG", "")  # "", or "1".."5"
    # Each BASELINE config has a natural symbol count: sizing the lane axis
    # to the live symbols keeps the measurement about the flow shape, not
    # about dispatching a mostly-NOP grid (overridable via BENCH_SYMBOLS).
    cfg_symbols = {"1": 8, "2": 8, "3": 128}
    default_s = 64 if check else cfg_symbols.get(CFG, 10240)
    S = int(os.environ.get("BENCH_SYMBOLS", default_s))
    T = int(os.environ.get("BENCH_T", 4 if check else 16))
    # Single-symbol configs need a longer timeline for a meaningful
    # measurement: their dense rounds re-pack the one live lane 1024 deep,
    # so 48 grids would collapse into a single dispatch.
    cfg_grids = {"1": 1280, "2": 1280, "3": 480}
    default_g = 2 if check else int(cfg_grids.get(CFG, 48))
    G = int(os.environ.get("BENCH_GRIDS", default_g))
    # Per-op cost on the scan path is O(cap); a single-symbol book in the
    # config-1 crossing flow is a few levels deep, so the 256-slot default
    # (sized for 10K-symbol exchange load) would pay 4x the vector work for
    # nothing on the latency configs.
    cfg_cap = {"1": 64, "2": 256}
    default_cap = 32 if check else int(cfg_cap.get(CFG, 256))
    CAP = int(os.environ.get("BENCH_CAP", default_cap))
    # Default = the high-throughput configuration: VMEM-resident Pallas
    # kernel on int32 ticks. BENCH_DTYPE=int64 selects the exact-envelope
    # configuration (accuracy=8 with unbounded depth sums), which runs on
    # the scan path (Mosaic has no 64-bit lowering).
    default_kernel = "pallas" if DTYPE == "int32" else "scan"
    KERNEL = os.environ.get("BENCH_KERNEL", default_kernel)  # scan | pallas
    config = BookConfig(
        cap=CAP,
        max_fills=16,
        dtype=jnp.int32 if DTYPE == "int32" else jnp.int64,
    )

    if KERNEL == "pallas":
        from gome_tpu.ops import (
            default_block_s,
            pallas_available,
            pallas_batch_step,
        )

        interp = not pallas_available(config.dtype)
        if interp:  # interpret mode (CPU check) has no blocking constraint
            default_block = next(b for b in (128, 8, 1) if S % b == 0)
        else:
            default_block = default_block_s(S)
            if default_block is None:
                print(
                    f"# NOTE: S={S} has no valid compiled-kernel blocking; "
                    "falling back to the scan kernel",
                    file=sys.stderr,
                )
        block_s = (
            int(os.environ["BENCH_BLOCK_S"])
            if "BENCH_BLOCK_S" in os.environ
            else default_block
        )
    if KERNEL == "pallas" and block_s is None:
        KERNEL = "scan"
    if KERNEL == "pallas":
        stepper = jax.jit(
            lambda books, ops: pallas_batch_step(
                config, books, ops, block_s=block_s, interpret=interp
            ),
            donate_argnums=(0,),
        )
    else:
        stepper = jax.jit(
            lambda books, ops: batch_step(config, books, ops),
            donate_argnums=(0,),
        )

    # Per-grid device-side reduction of the outputs the host actually
    # watches during a bench: fills and overflow count. Per-grid sums fit
    # int32 comfortably (S*T*K < 2^31); the cross-grid total is accumulated
    # host-side in Python ints after ONE stacked fetch, so no wrap is
    # possible at any run length even with x64 off.
    fold = jax.jit(
        lambda o: jnp.stack([jnp.sum(o.n_fills), jnp.sum(o.book_overflow)])
    )
    add = jax.jit(lambda a, b: a + b)
    # Device accumulators are int32 when x64 is off; flush to host Python
    # ints often enough that the on-device partial stays under 2^31 for ANY
    # grid geometry (per-grid fills <= S*T*max_fills).
    per_grid_max = S * T * config.max_fills
    FLUSH_EVERY = max(1, min(256, (2**31 - 1) // max(per_grid_max, 1)))

    books = init_books(config, S)
    np_dtype = np.int32 if DTYPE == "int32" else np.int64
    if CFG:
        raw = build_config_grids(int(CFG), S, T, G + 2, dtype=np_dtype)
        # warmup consumes 2 grids; count only the timed ones
        timed_orders = sum(int((d["action"] != 0).sum()) for d in raw[2:])
    else:
        raw = build_grids(S, T, G + 2, dtype=np_dtype)
        timed_orders = S * T * G
    if DTYPE == "int32":
        # int32 mode uses coarser lot units so per-side depth totals stay
        # far from 2^31 (the documented int32-mode operating contract).
        for d in raw:
            d["volume"] = (d["volume"] // 1_000_000).astype(np_dtype)
    # Compiled-kernel parity gate: three compiled-lowering crashes were
    # already found by fuzzing (the lowering is the risk surface), so every
    # TPU pallas bench certifies compiled == scan BEFORE timing and refuses
    # to report on mismatch. BENCH_PARITY=0 skips (e.g. repeated runs in
    # one session). CPU/interpret runs skip automatically.
    if (
        KERNEL == "pallas"
        and not check
        and os.environ.get("BENCH_PARITY", "1") != "0"
        and jax.default_backend() == "tpu"
        and pallas_available(config.dtype)  # the compiled kernel IS timed
    ):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
        from tpu_parity_check import run_parity

        rc = run_parity(
            S=128, T=8, CAP=CAP, K=config.max_fills, G=2,
            log=lambda m: print(f"# parity: {m}", file=sys.stderr),
        )
        if rc != 0:
            print(
                "# FATAL: compiled pallas kernel diverges from the scan "
                "path — refusing to report bench numbers",
                file=sys.stderr,
            )
            sys.exit(1)

    # Dense-round path for the sparse/latency-bound config shapes: 1-2
    # (single live lane — deep time axis amortizes dispatch) and 4 (Zipf —
    # device work must track APPLIED ops, not the 10K provisioned lanes).
    # Same packing strategy as the engine's dense path; BENCH_DENSE=0
    # forces the historical full-grid measurement.
    if CFG in ("1", "2", "4") and os.environ.get("BENCH_DENSE", "1") != "0":
        from gome_tpu.engine.batch import dense_batch_step, dense_kernel_step
        from gome_tpu.ops import default_block_s, pallas_available

        # Global depth ceiling; the packer additionally scales each round's
        # depth to the kernel's VMEM budget for its block size.
        t_dense = int(os.environ.get("BENCH_DENSE_T", 1024))
        warm_rounds = pack_dense_rounds(raw[:2], t_dense, S)
        timed_rounds = pack_dense_rounds(raw[2:], t_dense, S)
        use_kernel = KERNEL == "pallas" and pallas_available(config.dtype)

        def chain_fn(rounds):
            """One jitted program running a whole round chain: per-dispatch
            cost on a tunneled TPU is milliseconds, so the entire timeline
            must be ONE device dispatch — the unrolled trace chains every
            round's gather -> kernel -> scatter (or full-grid step)
            back-to-back on device."""
            from gome_tpu.ops import pallas_batch_step

            blocks = [
                default_block_s(S if ids is None else len(ids))
                if use_kernel
                else None
                for ids, _ in rounds
            ]

            def chain(books, rounds):
                acc = None
                for (ids, ops), bs in zip(rounds, blocks):
                    if ids is None:  # full-grid round (no gather/scatter)
                        if bs is not None:
                            books, outs = pallas_batch_step(
                                config, books, DeviceOp(**ops), block_s=bs
                            )
                        else:
                            books, outs = batch_step(
                                config, books, DeviceOp(**ops)
                            )
                    elif bs is not None:
                        books, outs = dense_kernel_step(
                            config, books, jnp.asarray(ids),
                            DeviceOp(**ops), bs,
                        )
                    else:
                        books, outs = dense_batch_step(
                            config, books, jnp.asarray(ids), DeviceOp(**ops)
                        )
                    f = jnp.stack(
                        [jnp.sum(outs.n_fills), jnp.sum(outs.book_overflow)]
                    )
                    acc = f if acc is None else acc + f
                return books, acc

            return jax.jit(chain, donate_argnums=(0,))

        warm_chain = chain_fn(warm_rounds)
        timed_chain = chain_fn(timed_rounds)
        stage = os.environ.get("BENCH_STAGED", "1") != "0"
        if stage:
            warm_rounds = jax.device_put(warm_rounds)
            timed_rounds = jax.device_put(timed_rounds)
            jax.block_until_ready(timed_rounds)

        books = init_books(config, S)
        books, acc = warm_chain(books, warm_rounds)  # steady-state books
        int(acc[0])
        books0 = jax.tree.map(jnp.copy, books)
        int(jnp.sum(books0.count))
        # Untimed pass: compile the timed chain.
        books, acc = timed_chain(jax.tree.map(jnp.copy, books0), timed_rounds)
        int(acc[0])

        # The timed region ends with ONE scalar fetch, which costs ~85ms
        # over the tunnel — far more than the device work of a single chain
        # at these config sizes. Chain the whole timeline CHAIN_REPS times
        # back-to-back (async dispatches pipeline; books carry over at
        # steady state) so the fetch amortizes to noise.
        chain_reps = int(
            os.environ.get(
                "BENCH_CHAIN_REPS", max(1, 1_000_000 // max(timed_orders, 1))
            )
        )
        REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
        elapsed = float("inf")
        overflows = 0
        for _ in range(max(1, REPEATS)):
            books = jax.tree.map(jnp.copy, books0)
            int(jnp.sum(books.count))  # barrier: copy completes off-clock
            acc = None
            t0 = time.perf_counter()
            for _ in range(chain_reps):
                books, a = timed_chain(books, timed_rounds)
                acc = a if acc is None else add(acc, a)
            totals = np.asarray(jax.device_get(acc), np.int64)
            pass_elapsed = time.perf_counter() - t0
            if pass_elapsed < elapsed:
                elapsed = pass_elapsed
                overflows = int(totals[1])
        if overflows:
            print(
                f"# WARNING: {overflows} book overflows at cap={CAP} — "
                "raise BENCH_CAP for an honest run",
                file=sys.stderr,
            )
        throughput = timed_orders * chain_reps / elapsed
        print(
            json.dumps(
                {
                    "metric": (
                        f"device matching throughput, config {CFG}, dense "
                        f"rounds over live lanes (t_dense={t_dense}), "
                        f"cap={CAP}, {DTYPE} ticks"
                    ),
                    "value": round(throughput),
                    "unit": "orders/sec",
                    "vs_baseline": round(throughput / 1_000_000, 3),
                }
            )
        )
        if os.environ.get("BENCH_VERBOSE"):
            shapes = [
                tuple(ops["action"].shape) for _, ops in timed_rounds
            ]
            print(
                f"# elapsed={elapsed:.3f}s applied={timed_orders} "
                f"x{chain_reps} reps, rounds={len(timed_rounds)} "
                f"shapes={shapes[:8]}... "
                f"platform={jax.devices()[0].platform}",
                file=sys.stderr,
            )
        return

    grids = [DeviceOp(**g) for g in raw]

    # Stage all grids on device before timing (BENCH_STAGED=0 to include
    # host->device transfer in the loop).
    if os.environ.get("BENCH_STAGED", "1") != "0":
        grids = [jax.device_put(g) for g in grids]
        jax.block_until_ready(grids)

    # Warmup: compile + 2 grids (also fills books to steady state, and warms
    # every graph the timed loop uses — nothing compiles inside the timing).
    # The scalar int() fetch is the only reliable completion barrier on
    # tunneled backends (block_until_ready can return at enqueue).
    books, outs = stepper(books, grids[0])
    acc = fold(outs)
    books, outs = stepper(books, grids[1])
    acc = add(acc, fold(outs))
    int(acc[0])

    # Repeat the timed chain and report the best pass: a single pass on a
    # shared/tunneled TPU can absorb external noise, and the recorded
    # number should reflect the device, not the neighbor. Each repeat
    # restarts from the same post-warmup book state (the donated chain
    # would otherwise keep deepening the books across repeats).
    REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
    books0 = jax.tree.map(jnp.copy, books)
    int(jnp.sum(books0.count))  # materialize the pristine copy off the clock
    elapsed = float("inf")
    total_fills = overflows = 0
    for _ in range(max(1, REPEATS)):
        books = jax.tree.map(jnp.copy, books0)
        int(jnp.sum(books.count))  # barrier: copy completes off the clock
        totals = np.zeros(2, np.int64)
        acc = None
        t0 = time.perf_counter()
        for i, grid in enumerate(grids[2:]):
            books, outs = stepper(books, grid)
            acc = fold(outs) if acc is None else add(acc, fold(outs))
            if (i + 1) % FLUSH_EVERY == 0:
                totals += np.asarray(jax.device_get(acc), np.int64)
                acc = None
        if acc is not None:
            # Final data-dependent fetch = the completion barrier.
            totals += np.asarray(jax.device_get(acc), np.int64)
        pass_elapsed = time.perf_counter() - t0
        if pass_elapsed < elapsed:
            elapsed = pass_elapsed
            total_fills = int(totals[0])
            # Passes replay identical grids from identical state; report
            # one pass's overflow count, not the sum over repeats.
            overflows = int(totals[1])

    if overflows:
        # A production engine escalates cap and replays (BatchEngine);
        # the bench must instead be configured so the budget never trips.
        print(
            f"# WARNING: {overflows} book overflows at cap={CAP} — raise "
            "BENCH_CAP for an honest run",
            file=sys.stderr,
        )
    orders = timed_orders
    throughput = orders / elapsed
    cfg_tag = f", config {CFG}" if CFG else ""
    result = {
        "metric": f"device matching throughput, {S} symbols x {T}-deep grids, cap={CAP}, {DTYPE} ticks, {KERNEL} kernel{cfg_tag}",
        "value": round(throughput),
        "unit": "orders/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
    }
    print(json.dumps(result))
    if os.environ.get("BENCH_VERBOSE"):
        print(
            f"# elapsed={elapsed:.3f}s orders={orders} "
            f"fills={total_fills} platform="
            f"{jax.devices()[0].platform}",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()

"""Benchmark: sustained device matching throughput, 10K-symbol exchange-scale
load on one TPU chip (BASELINE.json config 4 shape; north star >= 1M
orders/sec across 10K symbols on one v5e).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "orders/sec", "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.json "published":
{}), so the denominator is the north-star target itself — vs_baseline =
value / 1e6, i.e. the fraction of the 1M orders/sec goal achieved.

Method: S symbol lanes x T time slots of real limit orders (tight price
band around mid so flows cross and match constantly), packed host-side with
numpy, executed as G chained batch_step calls (scan over T x vmap over S)
with donated book state, synchronized per call (block_until_ready). Per-call
sync is the honest production shape — the consumer drains a micro-batch,
waits for results, publishes events — and avoids pathological pipelined
dispatch over tunneled-TPU transports. Grids are staged onto the device
before timing (BENCH_STAGED=0 to include host->device transfer in the
loop). Orders/sec counts every non-NOP op applied to a book. Run
`python bench.py --check` for a tiny self-check on any platform.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def build_grids(s, t, g, seed=0, dtype=np.int64):
    """G full [S, T] grids of crossing limit-order flow around mid=1.00
    (1e8 ticks at accuracy 8): uniform prices in ±0.5% of mid, volumes
    1..100 lots-of-1e6, random sides. Every slot is a live order."""
    rng = np.random.default_rng(seed)
    grids = []
    oid_base = 1
    for _ in range(g):
        price = rng.integers(99_500_000, 100_500_000, size=(s, t), dtype=dtype)
        volume = rng.integers(1, 101, size=(s, t), dtype=dtype) * 1_000_000
        side = rng.integers(0, 2, size=(s, t), dtype=np.int32)
        action = np.ones((s, t), np.int32)
        oid = (np.arange(s * t, dtype=dtype) + oid_base).reshape(s, t)
        oid_base += s * t
        uid = np.ones((s, t), dtype=dtype)
        grids.append(
            dict(
                action=action, side=side,
                is_market=np.zeros((s, t), np.int32),
                price=price, volume=volume, oid=oid, uid=uid,
            )
        )
    return grids


def main():
    check = "--check" in sys.argv
    import jax

    jax.config.update("jax_enable_x64", True)
    if check:
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_PLATFORM"):
        # Env JAX_PLATFORMS is consumed at interpreter start by this image's
        # sitecustomize; late override must go through jax.config.
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp

    S = int(os.environ.get("BENCH_SYMBOLS", 64 if check else 10240))
    T = int(os.environ.get("BENCH_T", 4 if check else 16))
    G = int(os.environ.get("BENCH_GRIDS", 2 if check else 12))
    CAP = int(os.environ.get("BENCH_CAP", 32 if check else 128))
    KERNEL = os.environ.get("BENCH_KERNEL", "scan")  # scan | pallas
    DTYPE = os.environ.get("BENCH_DTYPE", "int64")  # int64 | int32
    config = BookConfig(
        cap=CAP,
        max_fills=16,
        dtype=jnp.int32 if DTYPE == "int32" else jnp.int64,
    )

    if KERNEL == "pallas":
        from gome_tpu.ops import pallas_available, pallas_batch_step

        interp = not pallas_available()
        block_s = 8 if S % 8 == 0 else 1  # same fallback as BatchEngine._step
        stepper = jax.jit(
            lambda books, ops: pallas_batch_step(
                config, books, ops, block_s=block_s, interpret=interp
            ),
            donate_argnums=(0,),
        )
    else:
        stepper = jax.jit(
            lambda books, ops: batch_step(config, books, ops),
            donate_argnums=(0,),
        )

    books = init_books(config, S)
    np_dtype = np.int32 if DTYPE == "int32" else np.int64
    raw = build_grids(S, T, G + 2, dtype=np_dtype)
    if DTYPE == "int32":
        # int32 mode uses coarser lot units so per-side depth totals stay
        # far from 2^31 (the documented int32-mode operating contract).
        for d in raw:
            d["volume"] = (d["volume"] // 1_000_000).astype(np_dtype)
    grids = [DeviceOp(**g) for g in raw]

    # Warmup: compile + 2 grids (also fills books to steady state).
    books, outs = stepper(books, grids[0])
    jax.block_until_ready(books)
    books, outs = stepper(books, grids[1])
    jax.block_until_ready(books)

    timed = grids[2:]
    if os.environ.get("BENCH_STAGED", "1") != "0":
        timed = [jax.device_put(g) for g in timed]
        jax.block_until_ready(timed)

    t0 = time.perf_counter()
    for grid in timed:
        books, outs = stepper(books, grid)
        jax.block_until_ready(books)
    total_fills = jax.device_get(outs.n_fills).sum()
    elapsed = time.perf_counter() - t0

    orders = S * T * G
    throughput = orders / elapsed
    result = {
        "metric": f"device matching throughput, {S} symbols x {T}-deep grids, cap={CAP}, {DTYPE} ticks, {KERNEL} kernel",
        "value": round(throughput),
        "unit": "orders/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
    }
    print(json.dumps(result))
    if os.environ.get("BENCH_VERBOSE"):
        print(
            f"# elapsed={elapsed:.3f}s orders={orders} "
            f"last_grid_fills={int(total_fills)} platform="
            f"{jax.devices()[0].platform}",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()

"""Benchmark: sustained device matching throughput, 10K-symbol exchange-scale
load on one TPU chip (BASELINE.json config 4 shape; north star >= 1M
orders/sec across 10K symbols on one v5e).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "orders/sec", "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.json "published":
{}), so the denominator is the north-star target itself — vs_baseline =
value / 1e6, i.e. the fraction of the 1M orders/sec goal achieved.

Method: S symbol lanes x T time slots of real limit orders (tight price
band around mid so flows cross and match constantly), packed host-side with
numpy, executed as G chained batch_step calls (scan over T x vmap over S)
with donated book state. Synchronization discipline: the device runs the
G-grid chain without ANY host round trip; each grid's StepOutput is folded
into a device-side scalar accumulator (total fills + total overflows), and
ONE data-dependent scalar fetch closes the timed region. This matters
doubly on a tunneled TPU (host<->device round trips cost ~0.1-1s flat), and
it is also the production shape: the consumer keeps the device fed and
decodes event batches asynchronously, off the critical path. Orders/sec
counts every op applied to a book. Run `python bench.py --check` for a tiny
self-check on any platform.

Dtype note: the default is BENCH_DTYPE=int32 + the VMEM-resident Pallas
kernel — the high-throughput configuration, valid for workloads whose
tick/lot ranges keep per-side depth prefix sums under 2^31 (the bench's
int32 grids use coarser lot units accordingly). BENCH_DTYPE=int64 selects
the exact-integer envelope of the reference's accuracy=8 fixed-point
scaling (SURVEY §2.2) — prefix sums over a full (default 256-slot) side
can exceed 2^31 at 1e8-scaled lots — and runs on the scan path (Mosaic has
no 64-bit lowering).
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

import numpy as np


def build_grids(s, t, g, seed=0, dtype=np.int64):
    """G full [S, T] grids of crossing limit-order flow around mid=1.00
    (1e8 ticks at accuracy 8): uniform prices in ±0.5% of mid, volumes
    1..100 lots-of-1e6, random sides. Every slot is a live order."""
    rng = np.random.default_rng(seed)
    grids = []
    oid_base = 1
    for _ in range(g):
        price = rng.integers(99_500_000, 100_500_000, size=(s, t), dtype=dtype)
        volume = rng.integers(1, 101, size=(s, t), dtype=dtype) * 1_000_000
        side = rng.integers(0, 2, size=(s, t), dtype=np.int32)
        action = np.ones((s, t), np.int32)
        oid = (np.arange(s * t, dtype=dtype) + oid_base).reshape(s, t)
        oid_base += s * t
        uid = np.ones((s, t), dtype=dtype)
        grids.append(
            dict(
                action=action, side=side,
                is_market=np.zeros((s, t), np.int32),
                price=price, volume=volume, oid=oid, uid=uid,
            )
        )
    return grids


def build_config_grids(cfg, s, t, g, seed=0, dtype=np.int64):
    """BASELINE.json config shapes 1-5 (BENCH_CONFIG); grids are NOP-padded
    where a shape leaves slots idle (the caller counts action != 0 for its
    throughput denominator). The default bench path is build_grids: uniform
    full grids, the exchange-scale config-4 shape at peak device utilization.

      1  single-symbol limit cross (BUY sweeps resting asks; S=1 lane live)
      2  single-symbol mixed stream with partial fills + cancels
      3  100-symbol Poisson flow (only lanes 0..99 live, Poisson thinning)
      4  Zipf-skewed per-symbol arrival rates across all S lanes
      5  market + limit mix with multi-level depth-walk fills
    """
    rng = np.random.default_rng(seed)
    grids = []
    oid_base = 1
    for _ in range(g):
        d = dict(
            action=np.zeros((s, t), np.int32),
            side=np.zeros((s, t), np.int32),
            is_market=np.zeros((s, t), np.int32),
            price=np.zeros((s, t), dtype),
            volume=np.zeros((s, t), dtype),
            oid=np.zeros((s, t), dtype),
            uid=np.ones((s, t), dtype),
        )
        if cfg in (1, 2):
            mask = np.zeros((s, t), bool)
            mask[0, :] = True
        elif cfg == 3:
            lanes = min(100, s)
            mask = np.zeros((s, t), bool)
            mask[:lanes] = rng.random((lanes, t)) < 0.7  # Poisson thinning
        elif cfg == 4:
            ranks = np.arange(1, s + 1, dtype=np.float64)
            p_live = np.minimum(1.0, (1.0 / ranks) * 8)  # Zipf(1) rates
            mask = rng.random((s, t)) < p_live[:, None]
        else:  # 5
            mask = np.ones((s, t), bool)
        n = int(mask.sum())
        d["action"][mask] = 1
        d["side"][mask] = rng.integers(0, 2, n)
        if cfg == 1:
            # alternate resting asks and sweeping bids on the one lane
            tt = np.arange(t)
            d["side"][0] = (tt % 2 == 0).astype(np.int32)  # even: SALE rests
            d["price"][0] = np.where(
                tt % 2 == 0, 100_000_000 + (tt % 8) * 1000, 101_000_000
            )
            # Balanced flow: each sweeping bid consumes exactly the two
            # asks rested since the last one (5+5 lots) — the book hovers
            # at steady depth instead of accumulating a side without bound.
            d["volume"][0] = np.where(tt % 2 == 0, 5_000_000, 10_000_000)
        else:
            d["price"][mask] = rng.integers(99_500_000, 100_500_000, n)
            d["volume"][mask] = rng.integers(1, 101, n) * 1_000_000
        if cfg in (2, 5):
            # ~15% cancels of random earlier oids (misses allowed — the
            # reference's DeleteOrder on a filled order returns false)
            cm = mask & (rng.random((s, t)) < 0.15)
            d["action"][cm] = 2
            d["oid"][cm] = rng.integers(1, max(oid_base, 2), int(cm.sum()))
        if cfg == 5:
            mm = mask & (rng.random((s, t)) < 0.25) & (d["action"] == 1)
            d["is_market"][mm] = 1
        fresh = d["action"] == 1
        d["oid"][fresh] = oid_base + np.arange(int(fresh.sum()))
        oid_base += int(fresh.sum())
        grids.append(d)
    return grids


def _enable_jax_cache():
    """Persistent compilation cache: frame-geometry shapes drift with book
    state (pow2-bucketed, but a long run can still cross a bucket), and on
    a tunneled dev TPU one AOT compile costs tens of seconds — far too
    much to absorb inside a timed region. The cache makes every shape a
    one-time cost across processes AND runs (as in production)."""
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("GOME_JAX_CACHE", "/root/.cache/gome_jax"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # cache is an optimization, never fatal
        print(f"# jax compilation cache unavailable: {e}", file=sys.stderr)


def _next_pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


def _analytic_block(dtype_name):
    """Analytic XLA cost metrics (gome_tpu.obs.costmodel) folded into
    every BENCH payload: the BENCH_*.json snapshots then carry flops/order,
    bytes/order, arithmetic intensity, and peak HBM per hot-path entry —
    plus the donation savings — next to wall-clock orders/sec, so the
    analytic trajectory rides the same files as the measured one.
    BENCH_ANALYTIC=0 skips (e.g. repeated sweeps); failures degrade to a
    stderr note, never a broken bench."""
    if os.environ.get("BENCH_ANALYTIC", "1") == "0":
        return None
    try:
        from gome_tpu.obs import costmodel

        return costmodel.bench_analytics(dtype_name)
    except Exception as e:
        print(f"# analytic cost model unavailable: {e}", file=sys.stderr)
        return None


def _measured_block(dtype_name):
    """MEASURED roofline metrics (gome_tpu.obs.profiler) folded next to
    the analytic block in every BENCH payload: per-entry device time
    from a bounded jax.profiler capture, achieved GFLOP/s / GB/s
    (analytic work / measured time), and efficiency vs the machine
    ceiling — so BENCH_*.json carries what the hardware DID next to
    what XLA said it should do. BENCH_MEASURED=0 skips (captures cost
    seconds); failures degrade to a stderr note, never a broken bench."""
    if os.environ.get("BENCH_MEASURED", "1") == "0":
        return None
    try:
        from gome_tpu.obs import profiler

        return profiler.bench_measured(dtype_name)
    except Exception as e:
        print(f"# measured roofline unavailable: {e}", file=sys.stderr)
        return None


def _host_block():
    """Host-CPU admit metrics (gome_tpu.obs.hostprof) folded into the
    mixed-stream SERVICE payload next to the analytic/measured blocks:
    measured gateway admit ns/order + achievable orders/sec/core and
    the per-stage split from the sampling profiler's deterministic
    drill — so BENCH_SERVICE_*.json carries the host trajectory (the
    front-door bottleneck, ROADMAP open item 1) from r06 onward.
    BENCH_HOST=0 skips; failures degrade to a stderr note, never a
    broken bench."""
    if os.environ.get("BENCH_HOST", "1") == "0":
        return None
    try:
        from gome_tpu.obs import hostprof

        return hostprof.bench_host()
    except Exception as e:
        print(f"# host admit drill unavailable: {e}", file=sys.stderr)
        return None


def _admit_block():
    """Scalar-vs-columnar admit comparison (gome_tpu.obs.hostprof.
    bench_admit) folded into the mixed-stream SERVICE payload next to
    the host block: the IDENTICAL seeded flow through the single-order
    DoOrder path and the round-11 columnar DoOrderBatch core, side by
    side with the speedup ratio — so BENCH_SERVICE_*.json records the
    front-door rework's headline win. BENCH_ADMIT=0 skips; failures
    degrade to a stderr note, never a broken bench."""
    if os.environ.get("BENCH_ADMIT", "1") == "0":
        return None
    try:
        from gome_tpu.obs import hostprof

        return hostprof.bench_admit()
    except Exception as e:
        print(f"# admit bench unavailable: {e}", file=sys.stderr)
        return None


def admit_main():
    """--admit: the scalar-vs-columnar admit comparison standalone —
    host-only (no jax import, no engine), prints the bench_admit JSON
    payload. The fastest way to see the round-11 front-door numbers on
    any machine."""
    from gome_tpu.obs import hostprof

    doc = hostprof.bench_admit()
    print(json.dumps(doc, indent=1))
    s, c = doc["scalar"], doc["columnar"]
    print(
        f"# admit: scalar {s['admit_ns_per_order']} ns/order "
        f"({s['admit_orders_per_sec_per_core'] / 1e3:.0f}K/sec/core) vs "
        f"columnar {c['admit_ns_per_order']} ns/order "
        f"({c['admit_orders_per_sec_per_core'] / 1e3:.0f}K/sec/core) — "
        f"{doc['speedup_x']}x",
        file=sys.stderr,
    )
    return 0


def _jit_cache_sizes(**fns):
    """{name: compiled-variant count} for the bench's own jits — the
    payload's compile count (how many distinct shapes the timed chain
    minted). Best-effort: the probe is a jax-internal accessor."""
    out = {}
    for name, fn in fns.items():
        size = getattr(fn, "_cache_size", None)
        if callable(size):
            try:
                out[name] = size()
            except Exception:
                pass
    return out


FIELDS = ("action", "side", "is_market", "price", "volume", "oid", "uid")


def pack_dense_rounds(grids, t_dense, s_total, cap=None, depth_bound=None):
    """Convert NOP-padded [S, T] grids into dense rounds over LIVE lanes
    (the host-side packing the engine's dense path does —
    gome_tpu.engine.batch.dense_batch_step): per lane, concatenate its live
    ops across the whole timeline (FIFO preserved), then emit rounds of up
    to t_dense ops per still-live lane until every stream drains. Rows and
    time depth bucket to powers of two (bounded compile shapes); padding
    rows carry the out-of-range sentinel lane id = s_total.

    cap: the storage cap — when given, each round also gets a CAP CLASS
    (engine.batch._cap_ladder; VERDICT r4 #2): the smallest class covering
    every round lane's depth bound. A book can never hold more resting
    orders than the ops ever sent to it, so bounding by per-lane op totals
    is provably overflow-free for the tail of a Zipf flow while hot-lane
    rounds keep the full cap — the device stops paying one hot lane's
    depth on 10K shallow rows. depth_bound ([s_total] per-lane totals)
    lets the caller count ops across the WHOLE run (warmup + timed): a
    chain replaying from post-warmup books carries the warmup's resting
    depth, which this packer's own timeline cannot see. Defaults to this
    pack's totals. The engine-side guard (batch._guard_capped) still folds
    any violation into the overflow count the bench refuses to hide.

    Returns (rounds, caps): rounds = [(lane_ids[R]|None, ops dict of
    [R, T_d])], caps aligned per round (cap... repeated when cap=None).
    """
    from gome_tpu.engine.batch import _cap_ladder

    streams: dict[int, list] = {}
    for d in grids:
        live = d["action"] != 0
        for lane in np.nonzero(live.any(axis=1))[0]:
            m = live[lane]
            streams.setdefault(int(lane), []).append(
                {f: d[f][lane][m] for f in FIELDS}
            )
    merged = {
        lane: {f: np.concatenate([c[f] for c in chunks]) for f in FIELDS}
        for lane, chunks in streams.items()
    }
    total_len = {lane: len(m["action"]) for lane, m in merged.items()}
    ladder = _cap_ladder(cap) if cap else None
    use_classes = (
        ladder is not None
        and len(ladder) > 1
        and os.environ.get("BENCH_CAP_CLASSES", "1") != "0"
    )
    offsets = {lane: 0 for lane in merged}
    rounds = []
    caps = []

    def round_cap(lanes):
        if not use_classes:
            return cap
        if depth_bound is not None:
            bound = max(int(depth_bound[lane]) for lane in lanes)
        else:
            bound = max(total_len[lane] for lane in lanes)
        return next((c for c in ladder if c >= bound), ladder[-1])

    def emit(lanes, depth):
        # A round touching most lanes goes out as a FULL grid (lane_ids
        # None): a gather/scatter of nearly every row costs one DMA per row
        # on TPU — at 8K rows that dwarfs the matching work itself.
        if len(lanes) > s_total // 2:
            ops = {
                f: np.zeros(
                    (s_total, depth),
                    np.int32 if f in ("action", "side", "is_market")
                    else merged[lanes[0]][f].dtype,
                )
                for f in FIELDS
            }
            for lane in sorted(lanes):
                s0 = offsets[lane]
                chunk = {
                    f: merged[lane][f][s0 : s0 + depth] for f in FIELDS
                }
                n = len(chunk["action"])
                for f in FIELDS:
                    ops[f][lane, :n] = chunk[f]
                offsets[lane] += n
                if offsets[lane] >= len(merged[lane]["action"]):
                    del merged[lane], offsets[lane]
            caps.append(round_cap(lanes))
            rounds.append((None, ops))
            return
        # Min 8 rows: the Pallas kernel's sublane-alignment floor; sentinel
        # padding rows are free.
        rows = max(8, _next_pow2(len(lanes)))
        ops = {
            f: np.zeros(
                (rows, depth),
                np.int32 if f in ("action", "side", "is_market")
                else merged[lanes[0]][f].dtype,
            )
            for f in FIELDS
        }
        lane_ids = np.full(rows, s_total, np.int32)
        for r, lane in enumerate(sorted(lanes)):
            lane_ids[r] = lane
            s0 = offsets[lane]
            chunk = {f: merged[lane][f][s0 : s0 + depth] for f in FIELDS}
            n = len(chunk["action"])
            for f in FIELDS:
                ops[f][r, :n] = chunk[f]
            offsets[lane] += n
            if offsets[lane] >= len(merged[lane]["action"]):
                del merged[lane], offsets[lane]
        caps.append(round_cap(lanes))
        rounds.append((lane_ids, ops))

    while merged:
        # Per-dispatch cost on a tunneled TPU is milliseconds, so FEW FAT
        # rounds beat many tight ones. Each sweep emits at most two rounds:
        # every short-stream lane in one shallow depth-8 round (padding is
        # bounded 8x, and the whole round is one dispatch), and the deep
        # lanes in one round as deep as the kernel's VMEM budget allows for
        # their block size (record outputs are [T, K, block]) — a lane
        # appears at most once per sweep, so its chunks stay FIFO.
        shallow, deep, max_deep = [], [], 0
        for lane in merged:
            rem = len(merged[lane]["action"]) - offsets[lane]
            if rem <= 8:
                shallow.append(lane)
            else:
                deep.append(lane)
                max_deep = max(max_deep, rem)
        if shallow:
            emit(shallow, 8)
        if deep:
            block = min(max(8, _next_pow2(len(deep))), 128)
            t_vmem = (64 * 128) // block  # ~6MB of [T, K, block] records
            emit(deep, min(t_dense, t_vmem, _next_pow2(max_deep)))
    return rounds, caps


def _svc_columns(rng, n, n_symbols, oid0):
    """Raw order columns — what the gRPC handlers would have accumulated.
    Data GENERATION is the load client's job and stays off the clock; all
    gateway work on these columns (frame encode, pre-pool marking,
    publish) is timed. This is the CLEAN stream: 100% limit ADDs, uniform
    symbols, one uuid — the upper-bound measurement. The headline uses
    _svc_columns_mixed (the reference-driver-shaped flow)."""
    return dict(
        n=n,
        action=np.ones(n, np.uint8),
        side=rng.integers(0, 2, n).astype(np.uint8),
        kind=np.zeros(n, np.uint8),
        price=rng.integers(99_500_000, 100_500_000, n).astype(np.int64),
        volume=rng.integers(1, 101, n).astype(np.int64),
        symbol_idx=rng.integers(0, n_symbols, n).astype(np.uint32),
        uuid_idx=np.zeros(n, np.uint32),
        oids=np.char.add("o", np.arange(oid0, oid0 + n).astype("U12")).astype(
            "S"
        ),
    )


class _MixedFlow:
    """Config-5-shaped service load (the reference driver randomizes both
    sides and the new framework's config 5 adds markets + depth walks,
    doorder.go:38-47): ~45% cancels (a fifth of them targeting ADDs from
    the SAME frame, some ordered before their ADD — the
    cancel-before-consume race the pre-pool exists for, SURVEY §2.3.3),
    ~25% market orders among ADDs, 256 distinct uuids, Zipf(1) symbol
    popularity. Stateful: cancels target really-issued (symbol, oid,
    price) triples from a rolling pool of resting limit orders, biased
    to RECENT entries (most real cancels reprice fresh quotes).

    The cancel rate is chosen for depth-STATIONARITY: with this flow's
    rest rate (~55% x 75% limits x ~60% non-crossing), ~45% cancels is the
    equilibrium point where a hot Zipf lane's resting depth stays bounded
    (~300) instead of growing linearly and escalating book capacity
    forever — real exchange message mixes are majority-cancel (10:1+
    cancel-to-trade is common), so this is still conservative."""

    CANCEL_P = 0.45
    MARKET_P = 0.25
    SAME_FRAME_P = 0.2  # fraction of cancels aimed at this frame's ADDs
    RECENT_BIAS = 4  # pool cancels target the newest 1/4 of live entries
    N_UUIDS = 256
    POOL_MAX = 1 << 20

    def __init__(self, rng, n_symbols):
        self.rng = rng
        ranks = np.arange(1, n_symbols + 1, dtype=np.float64)
        w = 1.0 / ranks
        self.sym_p = w / w.sum()
        self.n_symbols = n_symbols
        self.oid0 = 1
        # Rolling pool of cancellable resting orders (ring buffer).
        self.pool_sym = np.zeros(self.POOL_MAX, np.uint32)
        self.pool_price = np.zeros(self.POOL_MAX, np.int64)
        self.pool_oid = np.zeros(self.POOL_MAX, np.int64)
        self.pool_uuid = np.zeros(self.POOL_MAX, np.uint32)
        self.pool_n = 0
        self.pool_head = 0

    def _pool_push(self, sym, price, oid, uuid):
        k = len(sym)
        idx = (self.pool_head + np.arange(k)) % self.POOL_MAX
        self.pool_sym[idx] = sym
        self.pool_price[idx] = price
        self.pool_oid[idx] = oid
        self.pool_uuid[idx] = uuid
        self.pool_head = (self.pool_head + k) % self.POOL_MAX
        self.pool_n = min(self.pool_n + k, self.POOL_MAX)

    def frame(self, n):
        rng = self.rng
        action = np.ones(n, np.uint8)
        dels = rng.random(n) < self.CANCEL_P
        if self.pool_n == 0:
            dels[:] = False
        action[dels] = 2
        adds = ~dels
        n_add = int(adds.sum())

        sym = rng.choice(
            self.n_symbols, size=n, p=self.sym_p
        ).astype(np.uint32)
        price = rng.integers(99_500_000, 100_500_000, n).astype(np.int64)
        volume = rng.integers(1, 101, n).astype(np.int64)
        kind = np.zeros(n, np.uint8)
        mkt = adds & (rng.random(n) < self.MARKET_P)
        kind[mkt] = 1
        oid_nums = np.zeros(n, np.int64)
        oid_nums[adds] = self.oid0 + np.arange(n_add)
        self.oid0 += n_add
        uuid_idx = rng.integers(0, self.N_UUIDS, n).astype(np.uint32)

        # Cancels carry the original order's (symbol, uuid, oid, price) —
        # the pre-pool key is S:U:O (ordernode.go:89-92) and the book
        # lookup needs the exact resting price (engine.go:92-93). Mostly
        # resting orders from earlier frames; some from THIS frame's
        # limit ADDs (the cancel-before-consume race when the DEL
        # precedes its ADD in the stream).
        di = np.nonzero(dels)[0]
        if len(di):
            same = rng.random(len(di)) < self.SAME_FRAME_P
            ai = np.nonzero(adds & (kind == 0))[0]
            if len(ai) == 0:
                same[:] = False
            n_pool = int((~same).sum())
            if n_pool:
                # Newest-quarter bias (ring indices count back from head).
                depth = max(self.pool_n // self.RECENT_BIAS, 1)
                back = rng.integers(1, depth + 1, n_pool)
                pi = (self.pool_head - back) % self.POOL_MAX
                tgt = di[~same]
                sym[tgt] = self.pool_sym[pi]
                price[tgt] = self.pool_price[pi]
                oid_nums[tgt] = self.pool_oid[pi]
                uuid_idx[tgt] = self.pool_uuid[pi]
            if same.any():
                ti = rng.integers(0, len(ai), int(same.sum()))
                src = ai[ti]
                tgt = di[same]
                sym[tgt] = sym[src]
                price[tgt] = price[src]
                oid_nums[tgt] = oid_nums[src]
                uuid_idx[tgt] = uuid_idx[src]

        rest = adds & (kind == 0)
        self._pool_push(
            sym[rest], price[rest], oid_nums[rest], uuid_idx[rest]
        )
        return dict(
            n=n,
            action=action,
            side=rng.integers(0, 2, n).astype(np.uint8),
            kind=kind,
            price=np.where(mkt, 0, price),
            volume=volume,
            symbol_idx=sym,
            uuid_idx=uuid_idx,
            oids=np.char.add(
                "o", oid_nums.astype("U12")
            ).astype("S"),
        )


class _SimFlow:
    """gome_tpu.sim traffic source (--flow sim / BENCH_FLOW=sim): the
    on-device Hawkes/Zipf generator drives the service bench instead of
    the hand-rolled _MixedFlow — clustered (self-exciting) arrivals,
    Zipf(a) symbol popularity, book-coupled limit placement, and cancels
    that target really-resting (symbol, uuid, oid, price) quadruples.
    Generation is the load client's job and stays off the clock: each
    pump runs one device gen step, applies the grid to a sim-side book
    stack (so later grids quote against the evolved state), then
    converts to the service column contract via sim.replay's
    grid_to_columns (deliberate-miss cancels dropped — the pre-pool
    tracks oid liveness). `.frame(n)` buffers pumps until it can hand
    out exactly n orders; the surplus carries into the next frame."""

    T_BINS = 1024  # one thinned event max per bin -> <= 1024 orders/pump

    def __init__(self, seed, n_symbols):
        import jax
        import jax.numpy as jnp

        from gome_tpu.engine.batch import batch_step
        from gome_tpu.engine.book import BookConfig, init_books
        from gome_tpu.sim.flow import FlowConfig, flow_init, gen_ops_jit
        from gome_tpu.sim.replay import grid_to_columns

        self._apply = batch_step
        self._gen = gen_ops_jit
        self._to_cols = grid_to_columns
        self._get = jax.device_get
        self.seed = seed
        self.config = FlowConfig(
            n_lanes=n_symbols,
            t_bins=self.T_BINS,
            ref_price=100_000_000,  # match the service price magnitude
            ref_spread=50,
        )
        # Generation-side books are independent of the engine under test
        # (the load client does not see the matcher's state); cap 64 is
        # deep enough that cancel targets come from a faithful book.
        self.book_config = BookConfig(cap=64, max_fills=8, dtype=jnp.int32)
        self.books = init_books(self.book_config, n_symbols)
        self.state = flow_init(self.config, jax.random.PRNGKey(seed))
        self._buf = []
        self._buffered = 0

    def _pump(self):
        self.state, ops = self._gen(self.config, self.state, self.books)
        self.books, _ = self._apply(self.book_config, self.books, ops)
        cols = self._to_cols(
            self._get(ops)._asdict(), drop_misses=True
        )
        if cols["n"]:
            self._buf.append(cols)
            self._buffered += cols["n"]

    def frame(self, n):
        while self._buffered < n:
            self._pump()
        cat = {
            k: np.concatenate([b[k] for b in self._buf])
            for k in self._buf[0]
            if k != "n"
        }
        out = {k: v[:n] for k, v in cat.items()}
        out["n"] = n
        rest = {k: v[n:] for k, v in cat.items()}
        m = len(rest["action"])
        self._buf = [dict(rest, n=m)] if m else []
        self._buffered = m
        return out

    def describe(self):
        """Flow provenance for the bench JSON payload (enough to rebuild
        the FlowConfig and regenerate the stream bit-exactly)."""
        c = self.config
        return {
            "kind": "sim",
            "seed": self.seed,
            "n_lanes": c.n_lanes,
            "t_bins": c.t_bins,
            "dt": c.dt,
            "rates": {
                "submit": c.submit_rate,
                "cancel": c.cancel_rate,
                "market": c.market_rate,
            },
            "hawkes": {
                "excite_self": c.excite_self,
                "excite_cross": c.excite_cross,
                "excite_kind": c.excite_kind,
                "decay": c.decay,
                "branching_ratio": round(c.branching_ratio(), 6),
            },
            "zipf_a": c.zipf_a,
            "offset_p": c.offset_p,
            "ref_price": c.ref_price,
        }


_SVC_UUIDS = [f"u{i}" for i in range(256)]  # shared uuid dictionary


def _svc_gateway_step(cols, symbols, pool, queue, uuids=_SVC_UUIDS):
    """The gateway's per-frame work, all ON the clock: wire-encode the
    frame (the batching DoOrder handler's output), mark the pre-pool
    (main.go:44-45 for every ADD), publish to doOrder."""
    from gome_tpu.bus.colwire import encode_order_frame

    cols = dict(cols, symbols=symbols, uuids=uuids)
    payload = encode_order_frame(
        cols["n"], cols["action"], cols["side"], cols["kind"],
        cols["price"], cols["volume"], symbols, cols["symbol_idx"],
        uuids, cols["uuid_idx"], cols["oids"],
    )
    mark_frame = getattr(pool, "mark_frame", None)
    if mark_frame is not None:
        mark_frame(cols)
    else:
        ADD = 1
        for a, k, u, o in zip(
            cols["action"].tolist(), cols["symbol_idx"].tolist(),
            cols["uuid_idx"].tolist(), cols["oids"].tolist(),
        ):
            if a == ADD:
                pool.add((symbols[k], uuids[u], o.decode()))
    queue.publish(payload)


def _svc_warmup(engine, consumer, bus, make_frame, symbols, margin=True):
    """Warm the service pipeline until its compiled shapes are pinned.

    Frame geometry (grid-2 packed rows/depth ratchets, compaction buffer
    classes) evolves as the books reach steady state, and every distinct
    shape is a trace+compile (tens of seconds AOT on the tunnel, ~1s of
    host CPU re-trace even cache-hit) — none of it belongs inside the
    timed region, exactly as a production deployment pre-warms its known
    geometry (BatchEngine.prewarm_geometry). Two phases:

      1. drain warm frames until the geometry ratchets hold still for two
         consecutive frames (min 2, max 8);
      2. the stochastic tails (live-lane count, per-lane depth, DEL count)
         can still cross a pow2 bucket mid-run, so pin the row/depth/
         cancel ratchets at 2x the observed steady state — far beyond any
         per-frame fluctuation — and run one more frame so the margined
         shapes compile too.

    make_frame() produces one frame's columns (a stateful generator —
    clean or mixed flow). Returns the number of warm frames consumed.

    margin=False (a run that loaded a persisted geometry manifest) skips
    phase 2: the loaded floors already carry a previous run's margin, and
    re-margining on every run would COMPOUND — 2x per run until the row
    floor exceeds n_slots and every tail class degenerates to a full
    grid (the r5 regression: floors hit 65536 on a 10240-lane book and
    each run minted fresh shapes forever instead of converging)."""
    n_warm = 0
    stable = 0
    # Minimum 8 warm frames regardless of ratchet stability: the BOOKS
    # also need to reach flow steady state (a crossing flow fills depth
    # over its first ~8 frames), and a manifest-loaded run whose floors
    # hold still from frame 1 must not start timing inside that book
    # transient — it would measure a different window of the flow than a
    # fresh run does.
    while n_warm < 8 or stable < 2:
        if n_warm >= 12:
            break
        cols = make_frame()
        geo = engine.batch.geometry_floors()
        _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
        consumer.drain()
        stable = stable + 1 if engine.batch.geometry_floors() == geo else 0
        n_warm += 1
    if not margin:
        return n_warm
    # The stability loop's ratchets include WARMUP TRANSIENTS (count_ub
    # overestimates while books fill send hundreds of lanes into a deep
    # cap class exactly once, latching e.g. a 1024-row x 1024-deep grid
    # floor that steady state never needs — seconds of device time per
    # frame, forever). Reset, let two steady-state frames re-ratchet
    # honest geometry, then pin the margin on THAT. The recorded shape
    # COMBOS from the transient frames are forgotten with the floors:
    # save_geometry would otherwise persist them and every later boot
    # would precompile deep-grid shapes the steady-state flow never uses.
    engine.batch.reset_geometry_floors(combos=True)
    for _ in range(2):
        _svc_gateway_step(
            make_frame(), symbols, engine.pre_pool, bus.order_queue
        )
        consumer.drain()
        n_warm += 1
    g = engine.batch.geometry_floors()
    engine.batch.prewarm_geometry(
        rows_floor={c: 2 * v for c, v in g["rows_floor"].items()},
        t_floor={c: 2 * v for c, v in g["t_floor"].items()},
        cancels_buf={b: 2 * v for b, v in g["cancels_buf"].items()},
        # fills_buf is dominated by pow2(grid n_ops) within each class —
        # no margin needed.
    )
    _svc_gateway_step(make_frame(), symbols, engine.pre_pool, bus.order_queue)
    consumer.drain()
    return n_warm + 1


def service_main():
    """End-to-end SERVICE bench: the full post-gRPC-arrival pipeline in
    one process — gateway side (frame encode + pre-pool mark + publish,
    timed) then consumer side (frame decode -> admission -> vectorized
    pack -> device matching -> device-side event compaction -> overlapped
    fetch (cross-frame pipelined) -> columnar decode -> EVENT-frame
    publish -> offset commit, timed). Only load GENERATION and compile
    warmup are off the clock.

    Prints ONE JSON line with the measured gateway->matchOrder number
    (gateway + consumer time combined — everything after gRPC arrival).
    On this dev environment the device link runs at single-digit MB/s
    (measured; a production TPU host attaches at PCIe speeds), so the
    stderr breakdown also reports the rate excluding time blocked on that
    fetch — the number the same pipeline sustains when the link is not
    the bottleneck — plus the gateway/consumer split (separate processes
    in the reference topology; serialized here on one host)."""
    check = "--check" in sys.argv
    import jax

    _enable_jax_cache()
    if check:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine import frames as engine_frames
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    N = int(os.environ.get("SVC_ORDERS", 8_192 if check else 1_048_576))
    FRAME = int(os.environ.get("SVC_FRAME", 2_048 if check else 262_144))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 10_240))
    CAP = int(os.environ.get("SVC_CAP", 32 if check else 256))
    PIPE = int(os.environ.get("SVC_PIPELINE", 2))  # cross-frame pipelining
    engine = MatchEngine(
        config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
        n_slots=S,
        max_t=32,
        kernel="pallas",
        # A Zipf frame's hottest lane runs ~30K ops deep; the kernel's
        # time-paged blocks make depth nearly free, so a deep ceiling
        # collapses the dense grid train (27 grids -> ~5) and with it the
        # per-grid dispatch + host cost.
        dense_t_max=int(os.environ.get("SVC_DENSE_T", 8192)),
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=PIPE,
    )

    # Clamp BEFORE the manifest key is built: a small-N run records
    # different frame-shape combos than a full-size run, so they must not
    # share one manifest file (keying on the pre-clamp FRAME did).
    FRAME = min(FRAME, N)

    # Persisted geometry (shape manifest): like a production deployment,
    # the service loads the flow's recorded floors + shape combos from the
    # previous run and precompiles them off-clock — the timed region then
    # contains zero first-seen traces (the XLA persistent cache already
    # made the compiles one-time; this closes the per-process TRACE gap).
    geom_path = os.environ.get(
        "SVC_GEOMETRY",
        os.path.join(
            os.environ.get("GOME_JAX_CACHE", "/root/.cache/gome_jax"),
            f"svc_geometry_S{S}_C{CAP}_F{FRAME}.json",
        ),
    )
    t0 = time.perf_counter()
    # The margin/reset warmup pass runs only when NO manifest exists:
    # keyed on file presence, not replay count — a manifest whose combos
    # are all above the boot cap replays 0 but its floors still loaded
    # and must not be reset + re-margined (compounding).
    have_manifest = os.path.exists(geom_path)
    # presize_cap=False: this one process runs BOTH streams, and the
    # shallow clean phase must not pay the mixed flow's stationary cap
    # from boot — the mixed warmup escalates off-clock (persistent-cache
    # reads) exactly like production would on first escalation.
    n_pre = engine.load_geometry(geom_path, presize_cap=False)
    if n_pre:
        print(
            f"# geometry manifest: {n_pre} shape combos precompiled in "
            f"{time.perf_counter() - t0:.1f}s ({geom_path})",
            file=sys.stderr,
        )

    rng = np.random.default_rng(7)
    symbols = [f"sym{i}" for i in range(S)]

    from gome_tpu.bus.colwire import decode_event_frame

    def run_stream(label, make_frame, repeats=1):
        """Warm (off clock) then time one stream REPEATS times: gateway
        phase + consumer drain per repeat. Returns the MEDIAN repeat's
        measurement dict (by throughput) extended with the per-run list,
        per-run getrusage deltas, and a per-frame consumer CPU-time
        histogram — VERDICT r5 #1/#2: a headline must be a median with
        contention telemetry attached, not a best-of-N outlier with no
        record of what the host was doing. process_time tracks the CPU
        this process actually spent (excludes time blocked on the tunnel
        AND CPU stolen by the tunnel proxy — the stable cost measure on
        a contended 1-core dev host)."""
        n_warm = _svc_warmup(
            engine, consumer, bus, make_frame, symbols,
            margin=not have_manifest,
        )
        runs = []
        cpu_frame: list[float] = []  # consumer CPU seconds per frame step
        for _rep in range(max(1, repeats)):
            frames_cols = [make_frame() for _ in range(-(-N // FRAME))]
            n_total = sum(int(c["n"]) for c in frames_cols)
            engine_frames.FETCH_SECONDS = 0.0
            ev_skip = bus.match_queue.end_offset()  # prior frames' events
            st0 = (
                engine.stats.device_calls,
                engine.stats.cap_escalations,
                engine.stats.frame_fallbacks,
            )
            ru0 = resource.getrusage(resource.RUSAGE_SELF)

            # Gateway phase (timed): encode + mark + publish every frame.
            t0 = time.perf_counter()
            for cols in frames_cols:
                _svc_gateway_step(
                    cols, symbols, engine.pre_pool, bus.order_queue
                )
            t_gateway = time.perf_counter() - t0

            # Consumer phase (timed), step by step: batch_n=1 means one
            # run_once ≈ one frame, so the per-step process_time delta IS
            # the per-frame CPU cost — the distribution the median
            # headline needs next to it (a flat median with a fat p99
            # tail is a contention story, not a throughput story).
            t0 = time.perf_counter()
            c0 = time.process_time()
            n_done = 0
            while (
                bus.order_queue.committed() < bus.order_queue.end_offset()
            ):
                s0 = time.process_time()
                n_step = consumer.run_once()
                dt = time.process_time() - s0
                if n_step:
                    cpu_frame.append(dt)
                n_done += n_step
            t_consumer = time.perf_counter() - t0
            cpu_consumer = time.process_time() - c0
            ru1 = resource.getrusage(resource.RUSAGE_SELF)
            fetch_s = engine_frames.FETCH_SECONDS
            elapsed = t_gateway + t_consumer
            assert n_done == n_total, (n_done, n_total)

            n_events = 0
            ev_bytes = 0
            for m in bus.match_queue.read_from(ev_skip, 1 << 30):
                ev_bytes += len(m.body)
                n_events += len(decode_event_frame(m.body))
            host_s = max(elapsed - fetch_s, 1e-9)
            runs.append(dict(
                label=label,
                orders=n_done,
                events=n_events,
                throughput=n_done / elapsed,
                ex_fetch=n_done / host_s,
                consumer_cpu_orders_per_sec_per_core=(
                    n_done / max(cpu_consumer, 1e-9)
                ),
                gateway_s=t_gateway,
                consumer_s=t_consumer,
                consumer_cpu_s=cpu_consumer,
                fetch_blocked_s=fetch_s,
                rusage=dict(
                    utime_s=round(ru1.ru_utime - ru0.ru_utime, 6),
                    stime_s=round(ru1.ru_stime - ru0.ru_stime, 6),
                    nvcsw=ru1.ru_nvcsw - ru0.ru_nvcsw,
                    nivcsw=ru1.ru_nivcsw - ru0.ru_nivcsw,
                    majflt=ru1.ru_majflt - ru0.ru_majflt,
                ),
            ))
            print(
                f"# [{label} {_rep + 1}/{max(1, repeats)}] "
                f"orders={n_done} events={n_events} "
                f"warm_frames={n_warm} gateway={t_gateway:.3f}s "
                f"consumer={t_consumer:.3f}s fetch_blocked={fetch_s:.3f}s "
                f"(dev-tunnel link) | ex-fetch "
                f"{n_done / host_s / 1e6:.2f}M orders/sec | "
                f"consumer-only ex-fetch "
                f"{n_done / max(t_consumer - fetch_s, 1e-9) / 1e6:.2f}M | "
                f"event-frame bytes/order={ev_bytes / max(n_done, 1):.1f} "
                f"| device_calls={engine.stats.device_calls - st0[0]} "
                f"escalations={engine.stats.cap_escalations - st0[1]} "
                f"fallbacks={engine.stats.frame_fallbacks - st0[2]} "
                f"cap={engine.config.cap} | "
                f"consumer_cpu={cpu_consumer:.3f}s -> "
                f"{n_done / max(cpu_consumer, 1e-9) / 1e6:.2f}M "
                f"orders/sec/core | nivcsw={runs[-1]['rusage']['nivcsw']}",
                file=sys.stderr,
            )
        ordered = sorted(runs, key=lambda r: r["throughput"])
        meas = dict(ordered[len(ordered) // 2])  # the median run
        meas["runs"] = runs
        meas["median_throughput"] = meas["throughput"]
        meas["best_throughput"] = ordered[-1]["throughput"]
        cf = np.asarray(cpu_frame, np.float64)
        if len(cf):
            p50, p90, p99 = np.percentile(cf, [50, 90, 99])
            meas["cpu_per_frame_s"] = dict(
                count=len(cf), mean=round(float(cf.mean()), 6),
                p50=round(float(p50), 6), p90=round(float(p90), 6),
                p99=round(float(p99), 6), max=round(float(cf.max()), 6),
            )
        return meas

    # Clean stream first (pure limit ADDs, uniform symbols — the upper
    # bound), then the HEADLINE mixed stream (reference-driver shape:
    # Zipf symbols, ~45% cancels incl. same-frame races, ~25% markets,
    # 256 uuids). Clean-first also means the mixed phase's extra compiled
    # shapes (deep dense grids for hot Zipf lanes, cancel buffers) are
    # charged to the mixed warmup, not the clean timed region.
    oid_box = [1]

    def clean_frame():
        cols = _svc_columns(rng, FRAME, S, oid_box[0])
        oid_box[0] += FRAME
        return cols

    clean = run_stream("clean", clean_frame)
    # Headline traffic source: the hand-rolled reference-driver-shaped
    # _MixedFlow (default), or the gome_tpu.sim Hawkes/Zipf generator
    # (--flow sim / BENCH_FLOW=sim) — same column contract, but with
    # clustered arrivals and book-coupled placement, and with its full
    # provenance (seed + model params) recorded in the payload.
    flow_kind = os.environ.get("BENCH_FLOW", "mixed")
    if "--flow" in sys.argv:
        flow_kind = sys.argv[sys.argv.index("--flow") + 1]
    if flow_kind == "sim":
        head_flow = _SimFlow(int(os.environ.get("SVC_SIM_SEED", 11)), S)
        flow_info = head_flow.describe()
        flow_label = "SIM Hawkes/Zipf"
    elif flow_kind == "mixed":
        head_flow = _MixedFlow(np.random.default_rng(11), S)
        flow_info = {
            "kind": "mixed",
            "seed": 11,
            "cancel_p": _MixedFlow.CANCEL_P,
            "market_p": _MixedFlow.MARKET_P,
            "same_frame_p": _MixedFlow.SAME_FRAME_P,
            "zipf_a": 1.0,
        }
        flow_label = "MIXED"
    else:
        raise SystemExit(f"unknown --flow {flow_kind!r} (mixed|sim)")
    # The HEADLINE is the MEDIAN of SVC_REPEATS timed repeats (VERDICT r5
    # #1/#2): one repeat is a sample, not a claim — the best repeat stays
    # in the payload as a secondary field, next to the per-run rusage
    # deltas (nivcsw = the contention record) and the per-frame CPU
    # histogram that say WHY the spread is what it is.
    REPEATS = int(os.environ.get("SVC_REPEATS", 5))
    mixed = run_stream(
        flow_kind, lambda: head_flow.frame(FRAME), repeats=REPEATS
    )
    try:
        engine.save_geometry(geom_path)
    except OSError as e:
        print(f"# geometry manifest not saved: {e}", file=sys.stderr)

    throughput = mixed["median_throughput"]
    result = {
        "metric": (
            f"service throughput gateway->matchOrder, {flow_label} "
            "stream "
            f"(Zipf symbols, cancels + market orders, 256 uuids; "
            f"everything after gRPC arrival), "
            f"{S} symbols, {FRAME}-order frames, int32 pallas, pipeline "
            f"depth {PIPE}; MEDIAN of {REPEATS} timed repeats"
        ),
        "flow": flow_info,
        "value": round(throughput),
        "unit": "orders/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
        "best_of_runs": round(mixed["best_throughput"]),
        "runs": [
            {
                "throughput": round(r["throughput"]),
                "consumer_cpu_orders_per_sec_per_core": round(
                    r["consumer_cpu_orders_per_sec_per_core"]
                ),
                "gateway_s": round(r["gateway_s"], 3),
                "consumer_s": round(r["consumer_s"], 3),
                "fetch_blocked_s": round(r["fetch_blocked_s"], 3),
                "rusage": r["rusage"],
            }
            for r in mixed["runs"]
        ],
        "cpu_per_frame_s": mixed.get("cpu_per_frame_s"),
    }
    analytic = _analytic_block("int32")
    if analytic is not None:
        # The drill's own compile trajectory: how many distinct dispatch
        # shape combos this flow minted (the perf ratchet gates the
        # scripted-drill equivalent).
        analytic["compiled_frame_combos"] = engine.batch.combo_count()
        result["analytic"] = analytic
    measured = _measured_block("int32")
    if measured is not None:
        result["measured"] = measured
    host = _host_block()
    if host is not None:
        result["host"] = host
    admit = _admit_block()
    if admit is not None:
        result["admit"] = admit
    print(json.dumps(result))
    print(
        f"# mixed vs clean: on-link {mixed['throughput'] / 1e3:.0f}K vs "
        f"{clean['throughput'] / 1e3:.0f}K orders/sec | consumer CPU "
        f"{mixed['consumer_cpu_orders_per_sec_per_core'] / 1e6:.2f}M vs "
        f"{clean['consumer_cpu_orders_per_sec_per_core'] / 1e6:.2f}M "
        f"orders/sec/core",
        file=sys.stderr,
    )


def latency_main():
    """--latency: order->publish latency vs frame size, pipeline depth
    held constant (the throughput/latency trade-off curve; the reference
    is fully async and publishes no latency numbers — main.go:49 — so
    this sets the bar).

    Method: a closed-loop steady-state run per frame size — the gateway
    publishes a frame, then the consumer takes one step (with cross-frame
    pipelining, up to `depth` frames stay in flight), so frames complete
    while later ones are being produced, exactly like production.
    Completion times attribute FIFO (frames resolve in order). An order's
    latency = its frame's publish-completion time minus its synthetic
    arrival time: arrivals are spread uniformly over the frame's
    accumulation window at the run's own sustained rate (an order that
    arrives just after a frame closes waits a full accumulation window —
    the batching bridge's cost, which this measurement deliberately
    includes; SURVEY L4: who batches and at what latency cost).

    Prints one JSON line per frame size with throughput and
    p50/p99/p99.9 order->publish latency."""
    check = "--check" in sys.argv
    import jax

    _enable_jax_cache()
    if check:
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_PLATFORM"):
        # BENCH_PLATFORM=cpu runs the closed loop with no tunnel in it:
        # the dev link's 1-3s RTT floors every on-TPU latency point, so
        # the CPU backend is the only honest way to validate the
        # pipeline's LATENCY STRUCTURE (accumulation + compute + decode)
        # with real clocks on this host (VERDICT r4 #7).
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import jax.numpy as jnp

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    from gome_tpu.utils.metrics import Registry
    from gome_tpu.utils.trace import TRACER, FlightRecorder

    N = int(os.environ.get("SVC_ORDERS", 8_192 if check else 1_048_576))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 10_240))
    CAP = int(os.environ.get("SVC_CAP", 32 if check else 256))
    PIPE = int(os.environ.get("SVC_PIPELINE", 2))
    sizes = (
        (512, 2048)
        if check
        else tuple(
            int(x)
            for x in os.environ.get(
                "SVC_LATENCY_FRAMES", "4096,32768,262144"
            ).split(",")
        )
    )
    symbols = [f"sym{i}" for i in range(S)]

    for frame_n in sizes:
        engine = MatchEngine(
            config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
            n_slots=S,
            max_t=32,
            kernel="pallas",
        )
        bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
        consumer = OrderConsumer(
            engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
            pipeline_depth=PIPE,
        )
        flow = _MixedFlow(np.random.default_rng(11), S)
        make_frame = lambda: flow.frame(frame_n)
        _svc_warmup(engine, consumer, bus, make_frame, symbols)

        # Per-stage breakdown (ISSUE 2): arm the order-lifecycle tracer
        # for the TIMED region only (warmup excluded), with a private
        # registry so frame sizes don't pollute each other. The drive
        # publishes raw frames (no per-order ids), so what lands here are
        # the batch-scoped engine/consumer stages — pad_pack,
        # compile_hit/miss, device_execute, decode, publish — i.e. WHERE
        # the end-to-end latency goes.
        TRACER.install(FlightRecorder(keep_n=8), registry=Registry())

        n_frames = max(PIPE + 2, N // frame_n)
        frames = [make_frame() for _ in range(n_frames)]
        pub_t: list = []  # publish time per frame, FIFO
        done_t: list = []
        t0 = time.perf_counter()
        for cols in frames:
            pub_t.append(time.perf_counter())
            _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
            n = consumer.run_once()
            now = time.perf_counter()
            for _ in range(n // frame_n):
                done_t.append(now)
        while len(done_t) < n_frames:
            n = consumer.run_once()
            now = time.perf_counter()
            for _ in range(n // frame_n):
                done_t.append(now)
        elapsed = time.perf_counter() - t0
        total = n_frames * frame_n
        rate = total / elapsed

        # Per-order latency: arrivals uniform over each frame's
        # accumulation window (ending at its publish) at the sustained
        # rate; completion = the frame's resolve+publish time.
        offs = (np.arange(frame_n, dtype=np.float64)[::-1] + 1) / rate
        lat = np.concatenate(
            [d - (p - offs) for p, d in zip(pub_t, done_t)]
        )
        p50, p99, p999 = np.percentile(lat, [50, 99, 99.9])

        # Corrected (intended-start) percentiles, ISSUE 17: the legacy
        # numbers above anchor each order to its frame's ACTUAL publish —
        # if the pipeline stalls, publishes slip with it and the queueing
        # delay never reaches the percentiles (coordinated omission). The
        # corrected recorder charges every order from a FIXED open-loop
        # schedule at the run's sustained rate anchored at run start.
        from gome_tpu.obs.capacity import LogHistogram, OpenLoopSchedule

        sched = OpenLoopSchedule(rate, t0=t0)
        chist = LogHistogram(rel_err=0.01, min_value=1e-7, max_value=600.0)
        for f, d in enumerate(done_t):
            base = f * frame_n
            for v in (
                d - (t0 + (np.arange(frame_n) + base + 1) * sched.interval)
            ).tolist():
                chist.record(v if v > 0 else 0.0)
        cp50, cp99, cp999 = chist.percentiles((0.5, 0.99, 0.999))
        # Per-stage latency breakdown from the tracer's stage histograms:
        # the BENCH payload then records WHERE the end-to-end time goes
        # (batch-wait vs pack vs compile vs device vs decode vs publish),
        # not just that it went.
        stages = {
            stage: {
                "count": v["count"],
                "p50_us": round(v["p50"] * 1e6, 1),
                "p99_us": round(v["p99"] * 1e6, 1),
                "mean_us": round(v["mean"] * 1e6, 1),
            }
            for stage, v in sorted(TRACER.stage_summary().items())
        }
        TRACER.disable()
        print(
            json.dumps(
                {
                    "metric": (
                        f"order->publish latency, {frame_n}-order frames, "
                        f"mixed stream, pipeline depth {PIPE}, {S} symbols"
                    ),
                    "value": round(p99 * 1e3, 1),
                    "unit": "ms p99",
                    "throughput_orders_per_sec": round(rate),
                    "p50_ms": round(p50 * 1e3, 1),
                    "p99_ms": round(p99 * 1e3, 1),
                    "p999_ms": round(p999 * 1e3, 1),
                    "closed_loop": {
                        "p50_ms": round(p50 * 1e3, 1),
                        "p99_ms": round(p99 * 1e3, 1),
                        "p999_ms": round(p999 * 1e3, 1),
                        "method": "arrivals anchored to actual publishes",
                    },
                    "corrected": {
                        "p50_ms": round(cp50 * 1e3, 1),
                        "p99_ms": round(cp99 * 1e3, 1),
                        "p999_ms": round(cp999 * 1e3, 1),
                        "method": (
                            "open-loop intended schedule at sustained "
                            "rate (coordinated-omission-safe)"
                        ),
                        "histogram_rel_err": 0.01,
                    },
                    "stages": stages,
                }
            )
        )


def grpc_main():
    """--grpc: the measured gRPC front door — the real OrderGateway served
    over a real channel, driven by the pipelined doorder client (a
    separate process), with the FrameBatcher bridging requests into
    ORDER frames for the pipelined frame consumer (the production
    single-binary topology: client process | gateway+consumer process).

    NOTE on this host: ONE CPU core — the client process, the gRPC
    server threads, and the consumer timeshare it, so the number is the
    single-core capacity of the whole front door, not the gateway's
    parallel ceiling. The reference's only ingest is this path
    (main.go:22-64); it publishes no numbers to compare against."""
    check = "--check" in sys.argv
    import subprocess

    import jax

    _enable_jax_cache()
    if check:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.gateway import OrderGateway

    # MODE unary: one DoOrder RPC per order (the reference's only ingest
    # shape, main.go:39-52). MODE batch: the amortized DoOrderBatch RPC
    # with CLIENT_BATCH orders per request — the production front door.
    MODE = os.environ.get("SVC_GRPC_MODE", "batch")
    CLIENT_BATCH = int(os.environ.get("SVC_GRPC_CLIENT_BATCH", 1_024))
    default_n = 4_096 if check else (131_072 if MODE == "unary" else 1_048_576)
    N = int(os.environ.get("SVC_GRPC_ORDERS", default_n))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 1_024))
    CAP = int(os.environ.get("SVC_CAP", 64 if check else 256))
    PIPE = int(os.environ.get("SVC_PIPELINE", 2))
    BATCH = int(os.environ.get("SVC_GRPC_BATCH", 4_096))
    CONC = int(
        os.environ.get(
            "SVC_GRPC_CONCURRENCY", 128 if MODE == "unary" else 8
        )
    )

    engine = MatchEngine(
        config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
        n_slots=S,
        max_t=32,
        kernel="pallas",
        # Full grids only: the batcher's deadline flushes emit arbitrary
        # partial-frame sizes, and letting each pick its own dense-grid
        # geometry compiles a fresh kernel per size class — on a tunneled
        # dev TPU that is a 30s stall per shape. At 1024 uniform lanes the
        # full [S, max_t] grid is one compiled family and near-optimal.
        dense=False,
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=64, batch_wait_s=0.001, match_wire="frame",
        pipeline_depth=PIPE,
    )
    batcher = FrameBatcher(bus.order_queue, max_n=BATCH, max_wait_s=0.05)
    gateway = OrderGateway(
        bus, accuracy=8, mark=engine.mark, batcher=batcher
    )

    from concurrent import futures

    import grpc as _grpc

    from gome_tpu.api.service import add_order_servicer

    server = _grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    add_order_servicer(server, gateway)
    port = server.add_insecure_port("127.0.0.1:0")
    assert port != 0
    server.start()

    def run_client(n, seed):
        out = subprocess.run(
            [
                sys.executable, "-m", "gome_tpu.clients.doorder",
                f"127.0.0.1:{port}", str(n), str(CONC), str(S),
                "0.995", "1.005", "4", str(seed),
                str(CLIENT_BATCH if MODE == "batch" else 0),
            ],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    # Warmup: compile every shape off the clock.
    consumer.start()
    run_client(min(N, 4 * BATCH) + 1, seed=1)
    deadline = time.monotonic() + 300
    while bus.order_queue.committed() < bus.order_queue.end_offset() or len(
        consumer._pipe or ()
    ):
        batcher.flush()
        time.sleep(0.02)
        assert time.monotonic() < deadline, "warmup drain stalled"

    # Timed: client start -> every order matched and published.
    ev_skip = bus.match_queue.end_offset()
    c0 = time.process_time()
    t0 = time.perf_counter()
    stats = run_client(N + 1, seed=2)
    batcher.flush()
    deadline = time.monotonic() + 600
    while bus.order_queue.committed() < bus.order_queue.end_offset() or len(
        consumer._pipe or ()
    ):
        batcher.flush()
        time.sleep(0.005)
        assert time.monotonic() < deadline, "timed drain stalled"
    elapsed = time.perf_counter() - t0
    server_cpu = time.process_time() - c0
    consumer.stop()
    server.stop(0)

    from gome_tpu.bus.colwire import decode_event_frame

    n_events = sum(
        len(decode_event_frame(m.body))
        for m in bus.match_queue.read_from(ev_skip, 1 << 30)
    )
    rate = N / elapsed
    client_mode = (
        "DoOrderBatch x" + str(CLIENT_BATCH) if MODE == "batch"
        else "unary DoOrder"
    )
    print(
        json.dumps(
            {
                "metric": (
                    "gRPC-inclusive throughput: doorder client "
                    f"({client_mode}, "
                    f"concurrency {CONC}, separate process) -> real "
                    f"OrderGateway -> FrameBatcher({BATCH}) -> frame "
                    f"consumer -> matchOrder; {S} symbols, single-core "
                    "host (client+server+consumer timeshare)"
                ),
                "value": round(rate),
                "unit": "orders/sec",
                "vs_baseline": round(rate / 1_000_000, 3),
            }
        )
    )
    print(
        f"# client-side rate {stats['orders_per_s']:.0f}/s "
        f"(ok={stats['ok']} rejected={stats['rejected']}) | end-to-end "
        f"{rate:.0f}/s over {elapsed:.2f}s | events={n_events} | server "
        f"process CPU {server_cpu:.2f}s -> "
        f"{N / max(server_cpu, 1e-9) / 1e3:.0f}K orders/sec/core "
        "(gateway handlers + batcher + consumer combined)",
        file=sys.stderr,
    )


def _gateway_proc_main():
    """One gateway process for --grpc-scale: real gRPC server +
    OrderGateway + FrameBatcher publishing ORDER frames to its own file
    bus queue; pre-pool markers in the shared RESP server (the reference's
    gateway shape, main.go:22-52, horizontally replicated). Prints READY
    <port>, then waits for one stdin line and reports its process CPU."""
    busdir, resp_port, batch = sys.argv[2:5]
    from concurrent import futures

    import grpc as _grpc

    from gome_tpu.api.service import add_order_servicer
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.service.gateway import OrderGateway

    bus = make_bus(BusConfig(backend="file", dir=busdir))
    pool = RespPrePool(RespClient(port=int(resp_port)))

    def mark(order):
        pool.add((order.symbol, order.uuid, order.oid))

    def unmark(order):
        pool.discard((order.symbol, order.uuid, order.oid))

    batcher = FrameBatcher(
        bus.order_queue, max_n=int(batch), max_wait_s=0.05
    )
    gateway = OrderGateway(
        bus, accuracy=8, mark=mark, unmark=unmark, batcher=batcher
    )
    server = _grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    add_order_servicer(server, gateway)
    port = server.add_insecure_port("127.0.0.1:0")
    assert port != 0
    server.start()
    c0 = time.process_time()
    print(f"READY {port}", flush=True)
    sys.stdin.readline()  # parent signals: clients done
    batcher.flush()
    print(json.dumps({"cpu": time.process_time() - c0}), flush=True)
    batcher.close()
    server.stop(0)


def grpc_scale_main():
    """--grpc-scale: N gateway processes feeding ONE consumer (VERDICT r4
    #3's scaling table). Each gateway owns a gRPC port, a FrameBatcher,
    and a file-bus doOrder queue; a shared RESP server holds the pre-pool
    markers; each gateway gets its own batch-mode doorder client with a
    DISJOINT symbol namespace (per-symbol FIFO is then per-queue by
    construction). The consumer drains all N queues through one engine
    (CPU backend — the real chip cannot be shared with the service bench's
    pipeline, and ingest, not matching, is under test here).

    ONE host core: the N gateways timeshare it, so the table reports
    per-gateway-CORE rates (process CPU) — the multiplicative claim — and
    the measured aggregate wall rate as the single-core floor."""
    import shutil
    import subprocess
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient

    check = "--check" in sys.argv
    N_PER_GW = int(os.environ.get("SVC_GRPC_ORDERS", 4_096 if check else 262_144))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 256))
    CLIENT_BATCH = int(os.environ.get("SVC_GRPC_CLIENT_BATCH", 1_024))
    BATCH = int(os.environ.get("SVC_GRPC_BATCH", 4_096))
    CONC = int(os.environ.get("SVC_GRPC_CONCURRENCY", 8))
    sizes = [
        int(x)
        for x in os.environ.get(
            "SVC_GRPC_GATEWAYS", "1,2" if check else "1,2,4"
        ).split(",")
    ]
    here = os.path.dirname(os.path.abspath(__file__))
    rows = []
    for n_gw in sizes:
        root = tempfile.mkdtemp(prefix="gome_gwscale_")
        srv = subprocess.Popen(
            [sys.executable, "-m", "gome_tpu.persist.respserver",
             "--port", "0"],
            stdout=subprocess.PIPE, text=True,
        )
        gws: list = []
        clients: list = []
        try:
            ready = srv.stdout.readline().split()
            assert ready and ready[0] == "READY", ready
            resp_port = int(ready[1])
            busdirs = [os.path.join(root, f"gw{i}", "bus") for i in range(n_gw)]
            gws[:] = [
                subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--gateway-proc", busdirs[i], str(resp_port),
                     str(BATCH)],
                    stdout=subprocess.PIPE, stdin=subprocess.PIPE,
                    text=True, cwd=here,
                )
                for i in range(n_gw)
            ]
            ports = []
            for p in gws:
                line = p.stdout.readline().split()
                assert line and line[0] == "READY", line
                ports.append(int(line[1]))

            # One pipelined batch client per gateway, disjoint symbols.
            t0 = time.perf_counter()
            clients[:] = [
                subprocess.Popen(
                    [sys.executable, "-m", "gome_tpu.clients.doorder",
                     f"127.0.0.1:{ports[i]}", str(N_PER_GW + 1),
                     str(CONC), str(S), "0.995", "1.005", "4", str(3 + i),
                     str(CLIENT_BATCH), f"g{i}."],
                    stdout=subprocess.PIPE, text=True, cwd=here,
                )
                for i in range(n_gw)
            ]
            stats = []
            for c in clients:
                out, _ = c.communicate(timeout=1200)
                assert c.returncode == 0
                stats.append(json.loads(out.strip().splitlines()[-1]))
            for s in stats:  # fail at the point of failure, not downstream
                assert s.get("aborted", 0) == 0, s
            t_clients = time.perf_counter() - t0
            cpus = []
            for p in gws:
                p.stdin.write("done\n")
                p.stdin.flush()
                cpus.append(json.loads(p.stdout.readline())["cpu"])
                p.wait(timeout=60)

            # Consumer: one engine drains every gateway's queue (frames
            # interleave across queues; symbols are disjoint per queue so
            # per-symbol FIFO holds).
            engine = MatchEngine(
                config=BookConfig(cap=64, max_fills=16, dtype=jnp.int32),
                n_slots=max(1024, S * n_gw), max_t=32, kernel="scan",
            )
            engine.pre_pool = RespPrePool(RespClient(port=resp_port))
            from gome_tpu.bus.colwire import decode_order_frame

            buses = [
                make_bus(BusConfig(backend="file", dir=d)) for d in busdirs
            ]
            c0 = time.process_time()
            t0 = time.perf_counter()
            n_done = 0
            for bus in buses:
                q = bus.order_queue
                off = q.committed()
                while True:
                    msgs = q.read_from(off, 64)
                    if not msgs:
                        break
                    for m in msgs:
                        cols = decode_order_frame(m.body)
                        engine.process_frame(cols, fast=True)
                        n_done += int(cols["n"])
                    off = msgs[-1].offset + 1
                    q.commit(off)
            t_consume = time.perf_counter() - t0
            consumer_cpu = time.process_time() - c0
            total = sum(s["sent"] for s in stats)
            assert n_done == total, (n_done, total)
            rows.append(
                dict(
                    gateways=n_gw,
                    orders=total,
                    aggregate_wall_orders_per_sec=total / t_clients,
                    per_gateway_core_orders_per_sec=[
                        round(s["sent"] / max(c, 1e-9))
                        for s, c in zip(stats, cpus)
                    ],
                    client_rates=[round(s["orders_per_s"]) for s in stats],
                    consumer_drain_orders_per_sec=round(
                        n_done / max(t_consume, 1e-9)
                    ),
                    consumer_cpu_orders_per_sec_per_core=round(
                        n_done / max(consumer_cpu, 1e-9)
                    ),
                )
            )
            print(f"# gateways={n_gw}: {json.dumps(rows[-1])}",
                  file=sys.stderr)
        finally:
            # Reap EVERYTHING: a client timeout or a failed assert must
            # not orphan gateway/client processes onto the bench core.
            for p in clients + gws:
                if p.poll() is None:
                    p.terminate()
            for p in clients + gws:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            srv.terminate()
            srv.wait(timeout=10)
            shutil.rmtree(root, ignore_errors=True)
    best = max(rows, key=lambda r: sum(r["per_gateway_core_orders_per_sec"]))
    print(
        json.dumps(
            {
                "metric": (
                    "gRPC gateway scaling: N gateway processes "
                    f"(DoOrderBatch x{CLIENT_BATCH}, FrameBatcher "
                    f"{BATCH}) -> one consumer; single-core host, "
                    "per-gateway-core rates are process-CPU based"
                ),
                "value": round(
                    sum(best["per_gateway_core_orders_per_sec"])
                ),
                "unit": "orders/sec (sum of per-gateway-core rates)",
                "rows": rows,
            }
        )
    )


def _shard_consumer_main():
    """One sharded consumer process (spawned by --service --shards N):
    drains its shard's doOrder file queue through a full MatchEngine with
    the pre-pool in the shared RESP marker server — the reference's
    consumer process shape. Self-times the post-warmup drain and reports
    one JSON line on stdout."""
    import jax

    _enable_jax_cache()
    jax.config.update(
        "jax_platforms", os.environ.get("SVC_SHARD_PLATFORM", "cpu")
    )
    import jax.numpy as jnp

    busdir, resp_port, warm_orders, cap, n_slots, pipe = sys.argv[2:8]
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine import frames as engine_frames
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.service.consumer import OrderConsumer

    bus = make_bus(BusConfig(backend="file", dir=busdir))
    engine = MatchEngine(
        config=BookConfig(cap=int(cap), max_fills=16, dtype=jnp.int32),
        n_slots=int(n_slots),
        max_t=32,
        kernel="scan",
    )
    engine.pre_pool = RespPrePool(RespClient(port=int(resp_port)))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=int(pipe),
    )
    # Warmup (compiles) off the clock — synchronously (depth 0), so no
    # timed frame can be pipelined in flight before the clock starts.
    consumer.pipeline_depth = 0
    done = 0
    while done < int(warm_orders):
        done += consumer.run_once()
    consumer.pipeline_depth = int(pipe)
    events0 = engine.stats.fills + engine.stats.cancels
    print("READY", flush=True)
    go = os.path.join(busdir, "..", "..", "go")
    deadline = time.monotonic() + 300
    while not os.path.exists(go):
        if time.monotonic() > deadline:
            print(json.dumps({"error": "go-file timeout"}), flush=True)
            sys.exit(1)
        time.sleep(0.005)
    engine_frames.FETCH_SECONDS = 0.0
    t0 = time.perf_counter()
    c0 = time.process_time()
    n = consumer.drain()
    t_consumer = time.perf_counter() - t0
    cpu = time.process_time() - c0
    print(
        json.dumps(
            dict(
                orders=n,
                t_consumer=t_consumer,
                cpu=cpu,
                fetch_s=engine_frames.FETCH_SECONDS,
                events=engine.stats.fills + engine.stats.cancels - events0,
            )
        ),
        flush=True,
    )


def service_sharded_main(n_shards: int):
    """--service --shards N: the reference's full multi-process topology
    at scale — a shared RESP marker-server process, THIS process as the
    gateway (symbol-hash routing orders to per-shard doOrder file queues,
    marking the shared pre-pool, all timed), and N consumer processes
    each draining its shard through its own engine. Aggregate
    gateway->matchOrder throughput = N_orders / (gateway time + consumer
    wall time). NOTE: this host has ONE CPU core — the N consumers (and
    the marker server) timeshare it, so the aggregate here measures the
    topology's correctness and per-shard cost, not multiplicative
    scaling; on an M-core host each consumer owns a core (and in
    production its own TPU) and the aggregate multiplies."""
    import shutil
    import subprocess
    import tempfile

    check = "--check" in sys.argv
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.parallel.router import ShardRouter
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig

    # Sharded defaults are smaller than the single-process bench: the N
    # consumers run CPU-backend engines (the one real TPU chip cannot be
    # shared across processes; in production each shard owns a chip), and
    # CPU matching at the full 10K-lane geometry would measure XLA:CPU,
    # not the topology.
    N = int(os.environ.get("SVC_ORDERS", 8_192 if check else 262_144))
    FRAME = int(os.environ.get("SVC_FRAME", 2_048 if check else 32_768))
    S = int(os.environ.get("SVC_SYMBOLS", 64 if check else 2_048))
    CAP = int(os.environ.get("SVC_CAP", 32 if check else 64))
    PIPE = int(os.environ.get("SVC_PIPELINE", 2))
    FRAME = min(FRAME, N)
    N_WARM = 2

    root = tempfile.mkdtemp(prefix="gome_shard_bench_")
    procs: list = []
    srv = subprocess.Popen(
        [sys.executable, "-m", "gome_tpu.persist.respserver", "--port", "0"],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        ready = srv.stdout.readline().split()
        assert ready and ready[0] == "READY", ready
        resp_port = int(ready[1])

        router = ShardRouter(n_shards)
        symbols = [f"sym{i}" for i in range(S)]
        shard_of_sym = np.array(
            [router.route(s) for s in symbols], np.int64
        )
        busdirs = [os.path.join(root, f"shard{i}", "bus") for i in range(n_shards)]
        buses = [
            make_bus(BusConfig(backend="file", dir=d)) for d in busdirs
        ]
        pool = RespPrePool(RespClient(port=resp_port))

        rng = np.random.default_rng(7)
        oid0 = 1
        frames_cols = []
        for start in range(0, (N_WARM * n_shards) * FRAME + N, FRAME):
            n = min(FRAME, (N_WARM * n_shards) * FRAME + N - start)
            frames_cols.append(_svc_columns(rng, n, S, oid0))
            oid0 += n

        def gateway_step(cols):
            shards = shard_of_sym[cols["symbol_idx"]]
            for sh in range(n_shards):
                mask = shards == sh
                n_sh = int(mask.sum())
                if n_sh == 0:
                    continue
                sub = dict(
                    cols,
                    n=n_sh,
                    **{
                        k: np.ascontiguousarray(cols[k][mask])
                        for k in (
                            "action", "side", "kind", "price", "volume",
                            "symbol_idx", "uuid_idx", "oids",
                        )
                    },
                )
                _svc_gateway_step(
                    sub, symbols, pool, buses[sh].order_queue
                )

        n_warm_frames = N_WARM * n_shards
        warm_counts = [0] * n_shards
        for cols in frames_cols[:n_warm_frames]:
            shards = shard_of_sym[cols["symbol_idx"]]
            for sh in range(n_shards):
                warm_counts[sh] += int((shards == sh).sum())
            gateway_step(cols)

        # Publish the timed frames BEFORE starting consumers, timing the
        # gateway work by itself (on one core, concurrent phases would
        # just interleave; the reference runs these as separate hosts).
        t0 = time.perf_counter()
        for cols in frames_cols[n_warm_frames:]:
            gateway_step(cols)
        t_gateway = time.perf_counter() - t0

        procs[:] = [
            subprocess.Popen(
                [
                    sys.executable, os.path.abspath(__file__),
                    "--service-consumer", busdirs[i], str(resp_port),
                    str(warm_counts[i]), str(CAP), str(S), str(PIPE),
                ],
                stdout=subprocess.PIPE, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            for i in range(n_shards)
        ]
        for p in procs:
            line = p.stdout.readline().strip()
            assert line == "READY", line
        t0 = time.perf_counter()
        with open(os.path.join(root, "go"), "w"):
            pass
        reports = []
        for p in procs:
            reports.append(json.loads(p.stdout.readline()))
            p.wait(timeout=600)
        t_wall = time.perf_counter() - t0

        n_done = sum(r["orders"] for r in reports)
        fetch_s = sum(r["fetch_s"] for r in reports)
        elapsed = t_gateway + t_wall
        throughput = n_done / elapsed
        result = {
            "metric": (
                f"sharded service throughput gateway->matchOrder, "
                f"{n_shards} consumer processes + RESP marker server + "
                f"gateway (symbol-hash routed file buses), {S} symbols, "
                f"{FRAME}-order frames — single-core host: consumers "
                "timeshare one CPU"
            ),
            "value": round(throughput),
            "unit": "orders/sec",
            "vs_baseline": round(throughput / 1_000_000, 3),
        }
        print(json.dumps(result))
        per_shard = ", ".join(
            f"s{i}: {r['orders']}"
            f"@{r['orders'] / max(r['t_consumer'], 1e-9) / 1e3:.0f}K/s"
            f" (cpu {r['orders'] / max(r.get('cpu', 0), 1e-9) / 1e3:.0f}K/s/core)"
            for i, r in enumerate(reports)
        )
        # What M dedicated cores would deliver: each shard's measured CPU
        # cost, summed — the scaling claim grounded in this run's numbers.
        agg_cpu = sum(
            r["orders"] / max(r.get("cpu", 0), 1e-9) for r in reports
        )
        print(
            f"# orders={n_done} gateway={t_gateway:.3f}s consumers_wall="
            f"{t_wall:.3f}s fetch_blocked_sum={fetch_s:.3f}s | "
            f"aggregate-ex-fetch "
            f"{n_done / max(elapsed - fetch_s, 1e-9) / 1e6:.2f}M | "
            f"aggregate-at-{n_shards}-dedicated-cores "
            f"{agg_cpu / 1e6:.2f}M orders/sec | {per_shard}",
            file=sys.stderr,
        )
    finally:
        # Never orphan a consumer: a failure before the `go` file exists
        # would leave the others busy-polling forever.
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass
        srv.terminate()
        srv.wait(timeout=10)
        shutil.rmtree(root, ignore_errors=True)


def main():
    if "--service-consumer" in sys.argv:
        return _shard_consumer_main()
    if "--gateway-proc" in sys.argv:
        return _gateway_proc_main()
    if "--admit" in sys.argv:
        return admit_main()
    if "--latency" in sys.argv:
        return latency_main()
    if "--grpc-scale" in sys.argv:
        return grpc_scale_main()
    if "--grpc" in sys.argv:
        return grpc_main()
    if "--service" in sys.argv:
        if "--shards" in sys.argv:
            n = int(sys.argv[sys.argv.index("--shards") + 1])
            return service_sharded_main(n)
        return service_main()
    check = "--check" in sys.argv
    DTYPE = os.environ.get("BENCH_DTYPE", "int32")  # int64 | int32
    import jax

    _enable_jax_cache()

    # x64 only when the book dtype needs it: with x64 on, every jnp.arange /
    # Python-int literal inside the kernel promotes to int64, which Mosaic
    # (Pallas TPU) rejects and which doubles index-array traffic.
    if DTYPE == "int64":
        jax.config.update("jax_enable_x64", True)
    if check:
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_PLATFORM"):
        # Env JAX_PLATFORMS is consumed at interpreter start by this image's
        # sitecustomize; late override must go through jax.config.
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp

    CFG = os.environ.get("BENCH_CONFIG", "")  # "", or "1".."5"
    # Each BASELINE config has a natural symbol count: sizing the lane axis
    # to the live symbols keeps the measurement about the flow shape, not
    # about dispatching a mostly-NOP grid (overridable via BENCH_SYMBOLS).
    cfg_symbols = {"1": 8, "2": 8, "3": 128}
    default_s = 64 if check else cfg_symbols.get(CFG, 10240)
    S = int(os.environ.get("BENCH_SYMBOLS", default_s))
    T = int(os.environ.get("BENCH_T", 4 if check else 16))
    # Single-symbol configs need a longer timeline for a meaningful
    # measurement: their dense rounds re-pack the one live lane 1024 deep,
    # so 48 grids would collapse into a single dispatch.
    cfg_grids = {"1": 1280, "2": 1280, "3": 480}
    default_g = 2 if check else int(cfg_grids.get(CFG, 48))
    G = int(os.environ.get("BENCH_GRIDS", default_g))
    # Per-op cost on the scan path is O(cap); a single-symbol book in the
    # config-1 crossing flow is a few levels deep, so the 256-slot default
    # (sized for 10K-symbol exchange load) would pay 4x the vector work for
    # nothing on the latency configs.
    # Config 3's Poisson flow random-walks ~350 levels deep over its 480-
    # grid timeline: cap=512 runs it overflow-free (256 drops ~130K rests).
    cfg_cap = {"1": 64, "2": 256, "3": 512}
    default_cap = 32 if check else int(cfg_cap.get(CFG, 256))
    CAP = int(os.environ.get("BENCH_CAP", default_cap))
    # Default = the high-throughput configuration: VMEM-resident Pallas
    # kernel on int32 ticks. BENCH_DTYPE=int64 selects the exact-envelope
    # configuration (accuracy=8 with unbounded depth sums), which runs on
    # the scan path (Mosaic has no 64-bit lowering).
    default_kernel = "pallas" if DTYPE == "int32" else "scan"
    KERNEL = os.environ.get("BENCH_KERNEL", default_kernel)  # scan | pallas
    config = BookConfig(
        cap=CAP,
        max_fills=16,
        dtype=jnp.int32 if DTYPE == "int32" else jnp.int64,
    )

    if KERNEL == "pallas":
        from gome_tpu.ops import (
            default_block_s,
            pallas_available,
            pallas_batch_step,
        )

        interp = not pallas_available(config.dtype)
        if interp:  # interpret mode (CPU check) has no blocking constraint
            default_block = next(b for b in (128, 8, 1) if S % b == 0)
        else:
            default_block = default_block_s(S, CAP)
            if default_block is None:
                print(
                    f"# NOTE: S={S} has no valid compiled-kernel blocking; "
                    "falling back to the scan kernel",
                    file=sys.stderr,
                )
        block_s = (
            int(os.environ["BENCH_BLOCK_S"])
            if "BENCH_BLOCK_S" in os.environ
            else default_block
        )
    if KERNEL == "pallas" and block_s is None:
        KERNEL = "scan"
    if KERNEL == "pallas":
        stepper = jax.jit(
            lambda books, ops: pallas_batch_step(
                config, books, ops, block_s=block_s, interpret=interp
            ),
            donate_argnums=(0,),
        )
    else:
        stepper = jax.jit(
            lambda books, ops: batch_step(config, books, ops),
            donate_argnums=(0,),
        )

    # Per-grid device-side reduction of the outputs the host actually
    # watches during a bench: fills and overflow count. Per-grid sums fit
    # int32 comfortably (S*T*K < 2^31); the cross-grid total is accumulated
    # host-side in Python ints after ONE stacked fetch, so no wrap is
    # possible at any run length even with x64 off.
    fold = jax.jit(
        lambda o: jnp.stack([jnp.sum(o.n_fills), jnp.sum(o.book_overflow)])
    )
    add = jax.jit(lambda a, b: a + b)
    # Device accumulators are int32 when x64 is off; flush to host Python
    # ints often enough that the on-device partial stays under 2^31 for ANY
    # grid geometry (per-grid fills <= S*T*max_fills).
    per_grid_max = S * T * config.max_fills
    FLUSH_EVERY = max(1, min(256, (2**31 - 1) // max(per_grid_max, 1)))

    books = init_books(config, S)
    np_dtype = np.int32 if DTYPE == "int32" else np.int64
    if CFG:
        raw = build_config_grids(int(CFG), S, T, G + 2, dtype=np_dtype)
        # warmup consumes 2 grids; count only the timed ones
        timed_orders = sum(int((d["action"] != 0).sum()) for d in raw[2:])
    else:
        raw = build_grids(S, T, G + 2, dtype=np_dtype)
        timed_orders = S * T * G
    if DTYPE == "int32":
        # int32 mode uses coarser lot units so per-side depth totals stay
        # far from 2^31 (the documented int32-mode operating contract).
        for d in raw:
            d["volume"] = (d["volume"] // 1_000_000).astype(np_dtype)
    # Compiled-kernel parity gate: three compiled-lowering crashes were
    # already found by fuzzing (the lowering is the risk surface), so every
    # TPU pallas bench certifies compiled == scan BEFORE timing and refuses
    # to report on mismatch. BENCH_PARITY=0 skips (e.g. repeated runs in
    # one session). CPU/interpret runs skip automatically.
    if (
        KERNEL == "pallas"
        and not check
        and os.environ.get("BENCH_PARITY", "1") != "0"
        and jax.default_backend() == "tpu"
        and pallas_available(config.dtype)  # the compiled kernel IS timed
    ):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
        from tpu_parity_check import run_suite

        rc = run_suite(
            S=128, T=8, CAP=CAP, K=config.max_fills, G=2,
            log=lambda m: print(f"# parity: {m}", file=sys.stderr),
        )
        if rc != 0:
            print(
                "# FATAL: compiled pallas kernel diverges from the scan "
                "path — refusing to report bench numbers",
                file=sys.stderr,
            )
            sys.exit(1)

    # Dense-round path for the sparse/latency-bound config shapes: 1-2
    # (single live lane — deep time axis amortizes dispatch), 3 (100-lane
    # Poisson — merging each lane's timeline into depth-64 rounds cuts the
    # dispatch count ~6x vs 70%-occupied [128, 16] full grids, which were
    # dispatch-bound), and 4 (Zipf — device work must track APPLIED ops,
    # not the 10K provisioned lanes). Same packing strategy as the
    # engine's dense path; BENCH_DENSE=0 forces the historical full-grid
    # measurement.
    if CFG in ("1", "2", "3", "4") and os.environ.get("BENCH_DENSE", "1") != "0":
        from gome_tpu.engine.batch import dense_batch_step, dense_kernel_step
        from gome_tpu.ops import default_block_s, pallas_available

        # Global depth ceiling; the packer additionally scales each round's
        # depth to the kernel's VMEM budget for its block size.
        t_dense = int(os.environ.get("BENCH_DENSE_T", 1024))
        # Cap-class depth bound over warmup AND timed ops: the timed chain
        # replays from post-warmup books, so a lane's resting depth is
        # bounded by its op total across both phases, not the timed phase
        # alone.
        full_bound = sum((d["action"] != 0).sum(axis=1) for d in raw)
        warm_rounds, warm_caps = pack_dense_rounds(
            raw[:2], t_dense, S, CAP, depth_bound=full_bound
        )
        timed_rounds, timed_caps = pack_dense_rounds(
            raw[2:], t_dense, S, CAP, depth_bound=full_bound
        )
        use_kernel = KERNEL == "pallas" and pallas_available(config.dtype)

        def chain_fn(rounds, round_caps):
            """One jitted program running a whole round chain: per-dispatch
            cost on a tunneled TPU is milliseconds, so the entire timeline
            must be ONE device dispatch — the unrolled trace chains every
            round's gather -> kernel -> scatter (or full-grid step)
            back-to-back on device. Each round runs at ITS cap class (the
            dense steps slice the shared storage; engine.batch)."""
            import dataclasses

            from gome_tpu.engine.batch import full_kernel_step

            cfgs = [
                config if c == CAP else dataclasses.replace(config, cap=c)
                for c in round_caps
            ]
            blocks = [
                default_block_s(S if ids is None else len(ids), cfg.cap)
                if use_kernel
                else None
                for (ids, _), cfg in zip(rounds, cfgs)
            ]

            def chain(books, rounds):
                acc = None
                for (ids, ops), bs, cfg in zip(rounds, blocks, cfgs):
                    if ids is None:  # full-grid round (no gather/scatter)
                        if bs is not None:
                            books, outs = full_kernel_step(
                                cfg, books, DeviceOp(**ops), bs
                            )
                        else:
                            books, outs = batch_step(
                                cfg, books, DeviceOp(**ops)
                            )
                    elif bs is not None:
                        books, outs = dense_kernel_step(
                            cfg, books, jnp.asarray(ids),
                            DeviceOp(**ops), bs,
                        )
                    else:
                        books, outs = dense_batch_step(
                            cfg, books, jnp.asarray(ids), DeviceOp(**ops)
                        )
                    f = jnp.stack(
                        [jnp.sum(outs.n_fills), jnp.sum(outs.book_overflow)]
                    )
                    acc = f if acc is None else acc + f
                return books, acc

            # NOT donated: every rep replays the identical timeline from
            # the same post-warmup books0, so the input stack must survive
            # the call. XLA inserts exactly one protective copy inside the
            # compiled chain — far cheaper than the 7 per-leaf host
            # dispatches an eager reset costs over a tunneled link.
            return jax.jit(chain)

        warm_chain = chain_fn(warm_rounds, warm_caps)
        timed_chain = chain_fn(timed_rounds, timed_caps)
        stage = os.environ.get("BENCH_STAGED", "1") != "0"
        if stage:
            warm_rounds = jax.device_put(warm_rounds)
            timed_rounds = jax.device_put(timed_rounds)
            jax.block_until_ready(timed_rounds)

        books = init_books(config, S)
        books0, acc = warm_chain(books, warm_rounds)  # steady-state books
        int(acc[0])
        # Untimed pass: compile the timed chain.
        _, acc = timed_chain(books0, timed_rounds)
        int(acc[0])

        # The timed region ends with ONE scalar fetch, which costs ~85ms
        # over the tunnel — far more than the device work of a single chain
        # at these config sizes. Chain the whole timeline CHAIN_REPS times
        # back-to-back (async dispatches pipeline) so the fetch amortizes
        # to noise. Each rep REPLAYS the identical timeline from the same
        # post-warmup books (an async device-side copy, no host sync):
        # carrying books across reps deepened the Zipf hot lanes without
        # bound — ~108K silently dropped rests per r4-style run at
        # cap=256 — so the replay is both the honest measurement and the
        # overflow-free one.
        chain_reps = int(
            os.environ.get(
                "BENCH_CHAIN_REPS", max(1, 1_000_000 // max(timed_orders, 1))
            )
        )
        REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
        elapsed = float("inf")
        overflows = 0
        for _ in range(max(1, REPEATS)):
            int(jnp.sum(books0.count))  # barrier: state settled off-clock
            acc = None
            t0 = time.perf_counter()
            for _ in range(chain_reps):
                _, a = timed_chain(books0, timed_rounds)
                acc = a if acc is None else add(acc, a)
            totals = np.asarray(jax.device_get(acc), np.int64)
            pass_elapsed = time.perf_counter() - t0
            if pass_elapsed < elapsed:
                elapsed = pass_elapsed
                overflows = int(totals[1])
        if overflows:
            print(
                f"# WARNING: {overflows} book overflows at cap={CAP} — "
                "raise BENCH_CAP for an honest run",
                file=sys.stderr,
            )
        throughput = timed_orders * chain_reps / elapsed
        result = {
            "metric": (
                f"device matching throughput, config {CFG}, dense "
                f"rounds over live lanes (t_dense={t_dense}), "
                f"cap={CAP}, {DTYPE} ticks"
            ),
            "value": round(throughput),
            "unit": "orders/sec",
            "vs_baseline": round(throughput / 1_000_000, 3),
        }
        analytic = _analytic_block(DTYPE)
        if analytic is not None:
            analytic["compile_count"] = _jit_cache_sizes(
                chain=timed_chain
            ).get("chain")
            result["analytic"] = analytic
        measured = _measured_block(DTYPE)
        if measured is not None:
            result["measured"] = measured
        print(json.dumps(result))
        if os.environ.get("BENCH_VERBOSE"):
            shapes = [
                tuple(ops["action"].shape) for _, ops in timed_rounds
            ]
            print(
                f"# elapsed={elapsed:.3f}s applied={timed_orders} "
                f"x{chain_reps} reps, rounds={len(timed_rounds)} "
                f"shapes={shapes[:8]}... caps={timed_caps[:8]}... "
                f"platform={jax.devices()[0].platform}",
                file=sys.stderr,
            )
        return

    grids = [DeviceOp(**g) for g in raw]

    # Stage all grids on device before timing (BENCH_STAGED=0 to include
    # host->device transfer in the loop).
    if os.environ.get("BENCH_STAGED", "1") != "0":
        grids = [jax.device_put(g) for g in grids]
        jax.block_until_ready(grids)

    # Warmup: compile + 2 grids (also fills books to steady state, and warms
    # every graph the timed loop uses — nothing compiles inside the timing).
    # The scalar int() fetch is the only reliable completion barrier on
    # tunneled backends (block_until_ready can return at enqueue).
    books, outs = stepper(books, grids[0])
    acc = fold(outs)
    books, outs = stepper(books, grids[1])
    acc = add(acc, fold(outs))
    int(acc[0])

    # Repeat the timed chain and report the best pass: a single pass on a
    # shared/tunneled TPU can absorb external noise, and the recorded
    # number should reflect the device, not the neighbor. Each repeat
    # restarts from the same post-warmup book state (the donated chain
    # would otherwise keep deepening the books across repeats).
    REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
    books0 = jax.tree.map(jnp.copy, books)
    int(jnp.sum(books0.count))  # materialize the pristine copy off the clock
    elapsed = float("inf")
    total_fills = overflows = 0
    for _ in range(max(1, REPEATS)):
        books = jax.tree.map(jnp.copy, books0)
        int(jnp.sum(books.count))  # barrier: copy completes off the clock
        totals = np.zeros(2, np.int64)
        acc = None
        t0 = time.perf_counter()
        for i, grid in enumerate(grids[2:]):
            books, outs = stepper(books, grid)
            acc = fold(outs) if acc is None else add(acc, fold(outs))
            if (i + 1) % FLUSH_EVERY == 0:
                totals += np.asarray(jax.device_get(acc), np.int64)
                acc = None
        if acc is not None:
            # Final data-dependent fetch = the completion barrier.
            totals += np.asarray(jax.device_get(acc), np.int64)
        pass_elapsed = time.perf_counter() - t0
        if pass_elapsed < elapsed:
            elapsed = pass_elapsed
            total_fills = int(totals[0])
            # Passes replay identical grids from identical state; report
            # one pass's overflow count, not the sum over repeats.
            overflows = int(totals[1])

    if overflows:
        # A production engine escalates cap and replays (BatchEngine);
        # the bench must instead be configured so the budget never trips.
        print(
            f"# WARNING: {overflows} book overflows at cap={CAP} — raise "
            "BENCH_CAP for an honest run",
            file=sys.stderr,
        )
    orders = timed_orders
    throughput = orders / elapsed
    cfg_tag = f", config {CFG}" if CFG else ""
    result = {
        "metric": (
            f"device matching throughput, {S} symbols x {T}-deep "
            f"grids, cap={CAP}, {DTYPE} ticks, {KERNEL} kernel{cfg_tag}"
        ),
        "value": round(throughput),
        "unit": "orders/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
    }
    analytic = _analytic_block(DTYPE)
    if analytic is not None:
        analytic["compile_count"] = _jit_cache_sizes(
            stepper=stepper
        ).get("stepper")
        result["analytic"] = analytic
    measured = _measured_block(DTYPE)
    if measured is not None:
        result["measured"] = measured
    print(json.dumps(result))
    if os.environ.get("BENCH_VERBOSE"):
        print(
            f"# elapsed={elapsed:.3f}s orders={orders} "
            f"fills={total_fills} platform="
            f"{jax.devices()[0].platform}",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()

"""Live migration from a running gome deployment — over a real socket.

The reference's order book IS its Redis keyspace (SURVEY §2.1): sorted sets
for price levels, hashes for depth and FIFO linked lists, a comparison hash
for the pre-pool. This example plays both sides of a migration:

  1. stands up a Redis-compatible server (persist.respserver — substitute
     your real Redis host/port) and populates it with a book in the
     reference's EXACT key schema, as a live gome would have left it —
     including a pre-pool mark for an in-flight order;
  2. imports the whole keyspace into a TPU MatchEngine over the RESP socket
     (persist.restore_from_redis via the dependency-free RESP2 client);
  3. keeps matching: new orders cross the imported resting book, the
     imported pre-pool mark admits the in-flight ADD, and the event stream
     carries the reference's MatchResult semantics;
  4. exports the evolved book back out in the same schema
     (persist.redis_schema) so reference-side tooling keeps working.

    python examples/migrate_from_gome.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)

from gome_tpu.engine import BookConfig, MatchEngine
from gome_tpu.persist.redis_schema import export_to_redis
from gome_tpu.persist.redis_restore import restore_from_redis
from gome_tpu.persist.resp import RespClient
from gome_tpu.persist.respserver import FakeRedisServer
from gome_tpu.types import Action, Order, Side


def populate_reference_book(client: RespClient) -> None:
    """What a live gome leaves in Redis for eth2usdt: two resting asks
    (FIFO at one level via the linked-list hash), one bid, aggregate
    depth, and a pre-pool mark for an ADD still queued in RabbitMQ."""
    import json

    sym = "eth2usdt"

    def node(oid, uuid, side, price, volume, prev=None, nxt=None):
        return json.dumps({
            "Action": 1, "Uuid": uuid, "Oid": oid, "Symbol": sym,
            "Transaction": side, "Price": price, "Volume": volume,
            "Accuracy": 8, "NodeName": f"{sym}:node:{oid}",
            "IsFirst": prev is None, "IsLast": nxt is None,
            "PrevNode": f"{sym}:node:{prev}" if prev else "",
            "NextNode": f"{sym}:node:{nxt}" if nxt else "",
            "NodeLink": f"{sym}:link:{price}",
            "OrderHashKey": f"{sym}:comparison",
            "OrderHashField": f"{sym}:{uuid}:{oid}",
            "OrderListZsetKey": f"{sym}:{'BUY' if side == 0 else 'SALE'}",
            "OrderListZsetRKey": f"{sym}:{'SALE' if side == 0 else 'BUY'}",
            "OrderDepthHashKey": f"{sym}:depth",
            "OrderDepthHashField": f"{sym}:depth:{price}",
        }, separators=(",", ":"))

    ask = 60_000_000  # 0.60 at accuracy 8
    bid = 55_000_000
    client.execute_command("ZADD", f"{sym}:SALE", ask, str(ask))
    client.execute_command("ZADD", f"{sym}:BUY", bid, str(bid))
    client.execute_command(
        "HSET", f"{sym}:depth",
        f"{sym}:depth:{ask}", "700000000",
        f"{sym}:depth:{bid}", "200000000",
    )
    # FIFO at the ask level: a1 (older) then a2.
    client.execute_command(
        "HSET", f"{sym}:link:{ask}",
        "f", f"{sym}:node:a1", "l", f"{sym}:node:a2",
        f"{sym}:node:a1", node("a1", "alice", 1, ask, 300_000_000, nxt="a2"),
        f"{sym}:node:a2", node("a2", "bob", 1, ask, 400_000_000, prev="a1"),
    )
    client.execute_command(
        "HSET", f"{sym}:link:{bid}",
        "f", f"{sym}:node:b1", "l", f"{sym}:node:b1",
        f"{sym}:node:b1", node("b1", "carol", 0, bid, 200_000_000),
    )
    # An ADD accepted by the gateway but not yet consumed (nodepool.go:14-16).
    client.execute_command(
        "HSET", f"{sym}:comparison", f"{sym}:dave:inflight9", "1"
    )


def main() -> None:
    with FakeRedisServer() as server:  # substitute your real Redis here
        with RespClient(port=server.port) as client:
            populate_reference_book(client)

            engine = MatchEngine(
                config=BookConfig(cap=64, max_fills=8), n_slots=4, max_t=8
            )
            imported = restore_from_redis(engine, client)
            print(f"imported {imported} resting orders over RESP "
                  f"(port {server.port}); pre-pool marks: "
                  f"{sorted(engine.pre_pool)}")

            # The in-flight ADD drains from the queue: its imported mark
            # admits it and it crosses the imported asks.
            inflight = Order(uuid="dave", oid="inflight9", symbol="eth2usdt",
                             side=Side.BUY, price=60_000_000,
                             volume=500_000_000)
            for ev in engine.process([inflight]):
                t, m = ev.node, ev.match_node
                print(f"  FILL taker={t.oid} maker={m.oid} "
                      f"qty={ev.match_volume} @ {m.price}")

            # A fresh cancel with reference semantics (exact price needed).
            cancel = Order(uuid="carol", oid="b1", symbol="eth2usdt",
                           side=Side.BUY, price=55_000_000, volume=0,
                           action=Action.DEL)
            for ev in engine.process([cancel]):
                print(f"  CANCEL {ev.node.oid} remaining={ev.node.volume}")

            engine.batch.verify_books()

            # Export the evolved book back in the reference schema.
            client.flushdb()
            n_cmds = export_to_redis(engine, client=client)
            print(f"re-exported evolved book as {n_cmds} reference-schema "
                  f"commands; keys now: {sorted(client.keys('*'))}")


if __name__ == "__main__":
    main()

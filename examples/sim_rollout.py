"""Market-simulator rollout — the gome_tpu.sim zero→aha demo.

Runs a jitted `lax.scan` rollout of the gym-style environment (Hawkes/
Zipf background flow over vmapped books, everything on device), then
prints one JSON report: throughput (env steps/sec after warmup),
activity (events and trades per step, overflow counters), and the
statistical diagnostics that validate the flow model against its
configuration (Zipf exponent fit, empirical vs configured Hawkes
branching ratio, inter-window dispersion).

    python examples/sim_rollout.py --steps 200 --lanes 64 --out SIM.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=200, help="rollout length")
    ap.add_argument("--lanes", type=int, default=64, help="vmapped books")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="write the JSON report here")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from gome_tpu.engine.book import BookConfig
    from gome_tpu.sim import (
        EnvConfig, FlowConfig, env_reset, make_manifest, rollout,
    )
    from gome_tpu.sim import stats as sim_stats

    config = EnvConfig(
        flow=FlowConfig(n_lanes=args.lanes),
        book=BookConfig(cap=32, max_fills=8, dtype=jnp.int32),
    )
    key = jax.random.PRNGKey(args.seed)

    # Warm the compile off the clock, then time the steady-state scan.
    state, _ = env_reset(config, key)
    final, (rewards, info) = rollout(config, state, args.steps)
    jax.block_until_ready(info.checksum)
    state, _ = env_reset(config, key)
    t0 = time.perf_counter()
    final, (rewards, info) = rollout(config, state, args.steps)
    jax.block_until_ready(info.checksum)
    elapsed = time.perf_counter() - t0

    events, trades, b_over, f_over = jax.device_get(
        (info.events, info.trades, info.book_overflow, info.fill_overflow)
    )

    # Flow diagnostics on a fresh seeded sample (empty-book pricing —
    # occurrence/type/lane statistics are book-independent).
    n_grids = 400
    sample = sim_stats.sample_grids(config.flow, args.seed, n_grids)
    counts = sim_stats.symbol_counts(sample)
    per_grid = sim_stats.events_per_grid(sample)
    report = {
        "metric": (
            f"sim env rollout, {args.lanes} lanes x {args.steps} steps "
            f"(jitted lax.scan, background Hawkes/Zipf flow)"
        ),
        "manifest": make_manifest(config, args.seed, args.steps),
        "steps_per_sec": round(args.steps / elapsed, 2),
        "orders_per_sec": round(int(events.sum()) / elapsed),
        "events_per_step": round(float(events.mean()), 3),
        "trades_per_step": round(float(trades.mean()), 3),
        "book_overflow": int(b_over.sum()),
        "fill_overflow": int(f_over.sum()),
        "stats": {
            "n_sample_grids": n_grids,
            "zipf_a_configured": config.flow.zipf_a,
            "zipf_a_fit": round(sim_stats.zipf_exponent(counts), 4),
            "branching_configured": round(
                config.flow.branching_ratio(), 4
            ),
            "branching_empirical": round(
                sim_stats.empirical_branching_ratio(
                    config.flow, int(per_grid.sum()), n_grids
                ), 4
            ),
            "dispersion_index": round(
                sim_stats.dispersion_index(per_grid), 4
            ),
        },
    }
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Embedding the matching engine as a library — no gRPC, no queues.

Runs a mixed limit/market/cancel stream through the batched TPU engine and
prints fills, book depth, and engine counters. This is the minimal
"gome as a library" usage the reference never offered (its engine package is
inseparable from Redis/RabbitMQ); here the book is a value you own.

    python examples/embed.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.engine.book import book_depth
from gome_tpu.fixed import scale
from gome_tpu.types import Action, Order, OrderType, Side


def main():
    engine = BatchEngine(
        # int32 ticks + the Pallas kernel when on TPU; exact for any price
        # magnitude via per-lane rebasing (ARCHITECTURE.md "Numeric model").
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32),
        n_slots=2,
        kernel="pallas",
    )

    mk = lambda oid, side, price, vol, **kw: Order(
        uuid="alice", oid=oid, symbol="btc2usdt", side=side,
        price=scale(price), volume=scale(vol), **kw
    )
    orders = [
        mk("a1", Side.SALE, 100_000.0, 0.5),   # ask rests
        mk("a2", Side.SALE, 100_010.0, 0.7),   # deeper ask
        mk("b1", Side.BUY, 100_005.0, 0.6),    # crosses a1, partial a2? no:
        #   fills 0.5 @ 100000, remainder 0.1 rests as bid @ 100005
        mk("m1", Side.BUY, 0.0, 0.3, order_type=OrderType.MARKET),
        #   market: sweeps best ask (a2) for 0.3
        mk("a1x", Side.SALE, 99_990.0, 0.2),   # crosses the resting bid b1
        Order(uuid="alice", oid="a2", symbol="btc2usdt", side=Side.SALE,
              price=scale(100_010.0), volume=0, action=Action.DEL),
    ]

    batch = engine.process_columnar(orders)
    for ev in batch.to_results():
        kind = "CANCEL" if ev.is_cancel else "FILL  "
        print(
            f"{kind} taker={ev.node.oid:<4} maker={ev.match_node.oid:<4} "
            f"qty={ev.match_volume} @ {ev.match_node.price}"
        )

    books = engine.lane_books()
    lane = engine.symbol_lane("btc2usdt")
    for side, name in ((0, "bids"), (1, "asks")):
        import jax

        one = jax.tree.map(lambda a: a[lane], books)
        prices, vols, n = book_depth(one, side, max_levels=4)
        levels = [
            f"{int(prices[i])}x{int(vols[i])}" for i in range(int(n))
        ]
        print(f"{name}: {levels}")
    print(f"stats: {engine.stats}")
    engine.verify_books()
    print("book invariants OK")


if __name__ == "__main__":
    main()

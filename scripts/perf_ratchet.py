"""Perf-regression ratchet over DETERMINISTIC analytic device metrics.

Wall-clock throughput cannot gate CI (shared runners, no TPU), but the
engine's ANALYTIC cost surface can: XLA's per-executable flops / bytes
accessed / peak-HBM attribution (gome_tpu.obs.costmodel, at the canonical
envelope geometry) and the compile count of a scripted frame drill are
exact functions of the code — on the CPU backend they are bit-identical
run to run. This script gates them against the committed
``PERF_BASELINE.json`` exactly like gomelint's findings ratchet: a
regression (any gated metric grows past its tolerance) fails CI; an
improvement passes and prints a nudge to re-baseline; ``--update-baseline``
rewrites the file to the current values and the diff is reviewed like any
other code change.

    python scripts/perf_ratchet.py                    # gate (CI)
    python scripts/perf_ratchet.py --update-baseline  # re-baseline
    python scripts/perf_ratchet.py --report out.json  # machine-readable

Gated metrics:
  * ``<entry>.flops_per_order`` / ``<entry>.bytes_per_order`` /
    ``<entry>.peak_hbm_bytes`` per hot-path entry (batch_step,
    dense_batch_step, lane_scan, compact_accum, scatter_grid) — lower
    is better, analytic, bit-exact per jaxlib version;
  * ``frame_drill.compile_count`` — distinct dispatch shape combos a
    fixed scripted frame flow mints (BatchEngine.combo_count()): a
    shape-oscillation regression (the class of bug the grow-only
    geometry ratchets exist to prevent) shows up here as an extra
    compile, gated at tolerance 0;
  * ``gateway.admit_ns_per_order`` (lower is better) and
    ``gateway.admit_orders_per_sec_per_core`` (HIGHER is better) from
    the COLUMNAR admit drill (round 11's front door). These are
    wall-clock, so they gate with a deliberately huge wall-clock-
    tolerant headroom (3x the baseline, WALLCLOCK_TOLERANCE) — loose
    enough that shared-runner noise never trips it, tight enough that
    reintroducing a per-order Python loop into the batch admit path
    (a 7x regression) fails CI. Being host-only wall-clock they are
    jaxlib-version-INDEPENDENT and stay gated even when the XLA
    metrics degrade to advisory on a version mismatch.

Advisory (recorded in the report, NEVER gated): the drill's wall-clock
orders/sec, plus the skew surface of ROADMAP open item 2 — the drill's
measured ``gome_dispatched_rows_per_live_lane_p50`` and the
deterministic D=8 Zipf per-shard skew model — printed every run and
escalated to a WARNING line when a rows-per-live-lane p50 exceeds the
2.0 placement target, so skew regressions are loud in CI before the
placement fix lands. Also advisory: the SCALAR gateway admit surface
(``gateway.scalar_admit_*``) — the single-order DoOrder path the
columnar rework left intact — printed every run so the scalar-vs-
columnar gap trends in every CI log. And the GL8xx sharding surface
(``sharding.manifest_entries`` / ``sharding.gl8xx_findings``): the
committed shard-manifest entry count and the live sharding/partition
finding count, advisory here because gomelint's analysis job already
gates both (GL806 drift / new findings).

Toolchain drift: the XLA numbers are deterministic per jaxlib VERSION,
not across versions. The baseline records the jax version it was taken
with; on a mismatch the XLA metrics degrade to a loud warning (advisory)
while the version-independent rows — the compile count and the
wall-clock admit rows — stay gated; bumping jax then requires an
explicit ``--update-baseline`` commit.

Exit codes: 0 ok / baseline updated; 1 regression or missing baseline;
2 internal error.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(ROOT, "PERF_BASELINE.json")

#: Relative headroom per gated metric before a growth counts as a
#: regression. Compile count is exact by construction: one extra
#: compiled shape IS the regression.
DEFAULT_TOLERANCE = 0.02
#: surface.combo_universe_log2 is the GL905 universe's total cardinality
#: bound (log2 of the product of per-dimension value-set sizes) — pure
#: arithmetic over engine config bounds, independent of jax/jaxlib, so
#: it stays gated even on a version mismatch. Growth means the compile
#: surface widened (a config bound or quantizer changed); that is a
#: reviewed decision (--update-universe + --update-baseline), never
#: drift.
EXACT_METRICS = (
    "frame_drill.compile_count",
    "surface.combo_universe_log2",
)

#: Wall-clock admit rows (round 11): gated, but with 3x headroom —
#: limit = base * (1 + 2.0) for lower-is-better, base / (1 + 2.0) for
#: higher-is-better. Shared-runner jitter is ~1.5-2x at worst; the
#: regression this guards against (a per-order Python loop back in the
#: columnar admit path) is ~7x.
WALLCLOCK_TOLERANCE = 2.0
WALLCLOCK_GATED = (
    "gateway.admit_ns_per_order",
    "gateway.admit_orders_per_sec_per_core",
)
#: Gated metrics where GROWTH is the win and shrinking past the
#: tolerance floor is the regression.
HIGHER_BETTER = frozenset({"gateway.admit_orders_per_sec_per_core"})


def _drill_frame(n: int, n_symbols: int, seed: int, oid0: int) -> dict:
    import numpy as np

    rng = np.random.default_rng(seed)
    action = np.ones(n, np.int64)
    # deterministic cancel positions targeting earlier oids
    dels = rng.random(n) < 0.2
    action[dels] = 2
    return dict(
        n=n,
        action=action,
        side=rng.integers(0, 2, n).astype(np.int64),
        kind=np.zeros(n, np.int64),
        price=rng.integers(99_000, 101_000, n).astype(np.int64),
        volume=rng.integers(1, 50, n).astype(np.int64),
        symbols=[f"s{i}" for i in range(n_symbols)],
        symbol_idx=rng.integers(0, n_symbols, n).astype(np.int64),
        uuids=["u0", "u1"],
        uuid_idx=rng.integers(0, 2, n).astype(np.int64),
        oids=np.char.add(
            "o", np.arange(oid0, oid0 + n).astype("U8")
        ).astype("S"),
    )


def frame_drill() -> dict:
    """Scripted fast-path frame flow: fixed seeds, fixed sizes, fixed
    engine geometry — every dispatch shape combo it mints is a pure
    function of the packing/geometry code. Returns the gated compile
    count plus advisory wall-clock."""
    import jax.numpy as jnp

    from gome_tpu.engine import frames
    from gome_tpu.engine.batch import BatchEngine
    from gome_tpu.engine.book import BookConfig

    eng = BatchEngine(
        BookConfig(cap=64, max_fills=4, dtype=jnp.int32),
        n_slots=16, max_t=8,
    )
    n_orders = 0
    t0 = time.perf_counter()
    for i, n in enumerate((64, 64, 128, 64, 256, 128)):
        frames.apply_frame_fast(
            eng, _drill_frame(n, n_symbols=8, seed=100 + i, oid0=n_orders)
        )
        n_orders += n
    elapsed = time.perf_counter() - t0
    return {
        "gated": {
            "frame_drill.compile_count": eng.combo_count(),
        },
        "advisory": {
            "frame_drill.orders": n_orders,
            "frame_drill.wall_seconds": round(elapsed, 3),
            "frame_drill.orders_per_sec": round(n_orders / elapsed),
            "frame_drill.device_calls": eng.stats.device_calls,
            "frame_drill.frame_fallbacks": eng.stats.frame_fallbacks,
        },
    }


#: ROADMAP open item 2's placement target: p50 dispatched-rows per live
#: lane <= 2.0. Advisory-only until the placement fix lands — but LOUD
#: (a WARNING line in the CI log) whenever a skew metric exceeds it.
SKEW_TARGET = 2.0
SKEW_METRICS = (
    "gome_dispatched_rows_per_live_lane_p50",
    "zipf_d8.rows_per_live_lane_p50",
)


def skew_advisory() -> dict:
    """Per-shard skew surface (ROADMAP open item 2), ADVISORY only.

    Two sources: the drill's own measured dense-dispatch histogram
    (``gome_dispatched_rows_per_live_lane`` — frame_drill ran just
    before, so its p50 reflects this exact scripted flow), and the
    deterministic host-side D=8 Zipf packer model (the same per-shard
    MAX bucketing math ``scripts/mesh_overhead.py --skew`` sweeps, fixed
    seed) — so the 3.7x-class skew tax trends in every CI log before the
    placement fix lands, without needing a mesh on the runner."""
    import numpy as np

    from gome_tpu.engine.batch import _next_pow2, _rows_per_live_lane

    out = {
        "gome_dispatched_rows_per_live_lane_p50": round(
            _rows_per_live_lane.quantile(0.5), 4
        ),
    }
    rng = np.random.default_rng(7)
    s, d, draws = 1024, 8, 32
    local = s // d
    skews, rows_pll = [], []
    for _ in range(draws):
        lanes = np.unique(rng.zipf(1.1, size=256) % s)
        counts = np.bincount(lanes // local, minlength=d)
        r_s = max(8, _next_pow2(int(counts.max())))
        live = len(lanes)
        skews.append(int(counts.max()) * d / live)
        rows_pll.append(min(r_s * d, s) / live)
    out["zipf_d8.shard_skew_p50"] = round(float(np.median(skews)), 4)
    out["zipf_d8.rows_per_live_lane_p50"] = round(
        float(np.median(rows_pll)), 4
    )
    return out


def gateway_gated() -> tuple[dict, dict]:
    """COLUMNAR gateway admit rows (round 11) — GATED wall-clock.

    Sourced from obs.hostprof's deterministic seeded admit drill driven
    through the columnar ``DoOrderBatch`` core (the HOSTPROF_r02 flow at
    a CI-sized order count; the SAMPLING is what varies run to run, the
    measured ns/order is plain wall/N). Returns (gated, advisory). A
    drill failure returns no gated rows — the baseline's rows then read
    as "absent from the current run" and the ratchet fails loudly
    instead of passing silently."""
    try:
        from gome_tpu.obs import hostprof

        drill = hostprof.gateway_drill(
            n_orders=16_384, seed=11, min_samples=32, max_rounds=4,
            path="columnar", batch_n=1024,
        )
        gated = {
            "gateway.admit_ns_per_order": drill["admit_ns_per_order"],
            "gateway.admit_orders_per_sec_per_core": (
                drill["admit_orders_per_sec_per_core"]
            ),
        }
        advisory = {
            "gateway.hostprof_samples": drill["sampler"]["samples"],
            "gateway.hostprof_coverage_pct": drill["coverage_pct"],
        }
        return gated, advisory
    except Exception as exc:  # pragma: no cover - env-specific
        return {}, {"gateway.gated_error": f"{type(exc).__name__}: {exc}"}


def gateway_advisory() -> dict:
    """SCALAR gateway admit surface, ADVISORY only — the single-order
    DoOrder path the columnar rework (round 11) left intact, kept in
    every CI log so the scalar-vs-columnar gap trends. A drill failure
    degrades to an error row, never a broken ratchet."""
    try:
        from gome_tpu.obs import hostprof

        drill = hostprof.gateway_drill(
            n_orders=8192, seed=11, min_samples=64, max_rounds=2
        )
        return {
            "gateway.scalar_admit_ns_per_order": (
                drill["admit_ns_per_order"]
            ),
            "gateway.scalar_admit_orders_per_sec_per_core": (
                drill["admit_orders_per_sec_per_core"]
            ),
            "gateway.scalar_hostprof_samples": drill["sampler"]["samples"],
            "gateway.scalar_hostprof_coverage_pct": drill["coverage_pct"],
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"gateway.advisory_error": f"{type(exc).__name__}: {exc}"}


def recovery_advisory() -> dict:
    """Crash-recovery surface (ISSUE 11), ADVISORY only — wall-clock.

    Sourced from the committed chaos verdict (CHAOS_r01.json at the repo
    root, regenerated by scripts/chaos.py): recovery-time p50 and the
    WAL-replay rate measured across that run's kill/restart cycles. A
    missing or unreadable verdict degrades to an error row."""
    try:
        path = os.path.join(ROOT, "CHAOS_r01.json")
        with open(path) as f:
            verdict = json.load(f)
        rec = verdict["recovery"]
        return {
            "recovery.p50_s": rec["p50_s"],
            "recovery.wal_replay_frames_per_s": (
                rec["wal_replay_frames_per_s"]
            ),
            "recovery.kills": verdict["config"]["kills"],
            "recovery.verdict_pass": bool(verdict["pass"]),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"recovery.advisory_error": f"{type(exc).__name__}: {exc}"}


def fleet_advisory() -> dict:
    """Fleet-aggregate surface (round 10), ADVISORY only — wall-clock.

    Sourced from the committed fleet verdict (FLEET_r01.json at the repo
    root, regenerated by scripts/fleet_drill.py): aggregate orders/sec
    over the measured (post-warm-up) drive window, the stitched
    cross-process end-to-end latency p50, member count, and the verdict
    outcome. Never gateable — a shared CI runner's wall-clock is not a
    regression signal — but printed loudly every run so the fleet
    numbers ride along with the analytic ratchet."""
    try:
        path = os.path.join(ROOT, "FLEET_r01.json")
        with open(path) as f:
            verdict = json.load(f)
        table = verdict["table"]
        return {
            "fleet.orders_per_sec": table["fleet"]["orders_per_sec"],
            "fleet.stitched_p50_ms": table["e2e_latency_ms"]["p50"],
            "fleet.members": len(verdict["members"]),
            "fleet.partitions": verdict["config"]["partitions"],
            "fleet.verdict_pass": bool(verdict["pass"]),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"fleet.advisory_error": f"{type(exc).__name__}: {exc}"}


def fleet_chaos_advisory() -> dict:
    """Fleet fault-tolerance surface (round 12), ADVISORY only —
    wall-clock (never gated; a shared CI box cannot hold a recovery
    SLO, the machine-checked bound lives in the fleet_chaos job).

    Sourced from the committed fleet chaos verdict (FLEET_CHAOS_r01.json
    at the repo root, regenerated by scripts/fleet_chaos.py): member
    recovery-time p50 across the kill/restart cycles, the worst degraded
    window's aggregate throughput while a member was down, and the
    verdict outcome."""
    try:
        path = os.path.join(ROOT, "FLEET_CHAOS_r01.json")
        with open(path) as f:
            verdict = json.load(f)
        windows = verdict["throughput"]["degraded_windows"]
        worst = min(w["orders_per_s"] for w in windows.values())
        return {
            "fleet_chaos.recovery_p50_s": verdict["recovery"]["p50_s"],
            "fleet_chaos.degraded_orders_per_s_min": worst,
            "fleet_chaos.throughput_floor": (
                verdict["throughput"]["floor_orders_per_s"]
            ),
            "fleet_chaos.kills": verdict["config"]["kills"],
            "fleet_chaos.verdict_pass": bool(verdict["pass"]),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"fleet_chaos.advisory_error": f"{type(exc).__name__}: {exc}"}


def capacity_advisory() -> dict:
    """Capacity-observatory surface (ISSUE 17), ADVISORY only —
    wall-clock (never gated; the knee moves with the CI box, and a
    throughput number that can fail a build invites gaming the sweep).

    Sourced from the committed capacity verdict (CAPACITY_r01.json at
    the repo root, regenerated by ``scripts/capacity.py --fleet``):
    delivered throughput at the saturation knee, the corrected
    (coordinated-omission-safe) p99 at the ladder point nearest HALF
    the knee's offered rate (the healthy-operating-region latency a
    deployment should plan around), the stage the attribution table
    blames at the knee, and the verdict outcome."""
    try:
        path = os.path.join(ROOT, "CAPACITY_r01.json")
        with open(path) as f:
            verdict = json.load(f)
        knee = verdict["knee"]
        ladder = verdict["ladder"]
        half = knee["offered_per_sec"] / 2.0
        half_pt = min(
            ladder, key=lambda p: abs(p["offered_per_sec"] - half)
        )
        return {
            "capacity.knee_offered_per_sec": knee["offered_per_sec"],
            "capacity.knee_delivered_per_sec": knee["delivered_per_sec"],
            "capacity.corrected_p99_ms_at_half_knee": round(
                half_pt["corrected"]["p99_s"] * 1e3, 1
            ),
            "capacity.saturated_stage": knee["saturated_stage"],
            "capacity.ladder_points": len(ladder),
            "capacity.verdict_pass": bool(verdict["pass"]),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"capacity.advisory_error": f"{type(exc).__name__}: {exc}"}


def placement_advisory() -> dict:
    """Placement-observatory surface (ISSUE 20), ADVISORY only — never
    gated: the verdict is a host-side what-if prediction, and gating a
    prediction would ratchet the model instead of the engine.

    Sourced from the committed placement verdict (PLACEMENT_r01.json at
    the repo root, regenerated by ``scripts/placement_eval.py --out``):
    how concentrated the committed Zipf flow is (top-16 symbol share),
    the observed dense shard skew the replay reconciled against
    MULTICHIP_r06, and the best candidate policy's predicted skew — the
    gap between those last two is the placement headroom ROADMAP open
    item 2 leaves on the table, trended in every CI log."""
    try:
        from gome_tpu.obs.placement import load_verdict

        verdict = load_verdict(os.path.join(ROOT, "PLACEMENT_r01.json"))
        return {
            "placement.top16_share": verdict["workload"]["top16_share"],
            "placement.observed_shard_skew": (
                verdict["attribution"]["observed"]["shard_skew"]
            ),
            "placement.predicted_best_skew": (
                verdict["winner"]["predicted_shard_skew"]
            ),
            "placement.best_policy": verdict["winner"]["policy"],
            "placement.verdict_pass": bool(verdict["checks"]["pass"]),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"placement.advisory_error": f"{type(exc).__name__}: {exc}"}


#: The gomelint sweeps and the universe extraction below read the SOURCE
#: TREE, which is immutable for the life of a ratchet process — but the
#: in-process test harness calls collect() several times per process,
#: and re-running ~10s of AST analysis per call is pure waste. Cache per
#: process; the CI script runs collect() once anyway.
@functools.lru_cache(maxsize=None)
def _family_findings(family: str) -> int:
    from gome_tpu.analysis.core import run_paths

    return len(run_paths(
        [os.path.join(ROOT, "gome_tpu"),
         os.path.join(ROOT, "scripts"),
         os.path.join(ROOT, "bench.py")],
        select={family},
    ))


@functools.lru_cache(maxsize=1)
def _universe_log2() -> float:
    from gome_tpu.analysis.surface import extract_universe

    return float(extract_universe()["cardinality_log2_bound"])


def sharding_advisory() -> dict:
    """GL8xx sharding surface (ISSUE 18), ADVISORY only.

    Two rows: the committed sharding manifest's entry count (the GL806
    ratchet surface — a shrinking count means an entry point silently
    vanished from the traced/AST extraction and the manifest diff
    deserves a look) and the live GL8xx finding count over the same
    tree gomelint's CI invocation sweeps. Both already FAIL CI through
    gomelint when they drift/regress; the advisory rows just put the
    trend in every perf log. Never gated here — the gate belongs to
    the analysis job."""
    try:
        from gome_tpu.analysis.sharding import DEFAULT_MANIFEST, load_manifest

        manifest = load_manifest(os.path.join(ROOT, DEFAULT_MANIFEST))
        return {
            "sharding.manifest_entries": (
                len(manifest["entries"]) if manifest else 0
            ),
            "sharding.gl8xx_findings": _family_findings("GL8"),
        }
    except Exception as exc:  # pragma: no cover - env-specific
        return {"sharding.advisory_error": f"{type(exc).__name__}: {exc}"}


def surface_metrics() -> tuple[dict, dict]:
    """GL9xx compile-surface rows (ISSUE 19): (gated, advisory).

    Gated: the combo universe's total cardinality bound in log2 —
    exact and jax-version-independent (see EXACT_METRICS). Advisory:
    the committed universe's dimension count (a shrinking count means a
    combo field silently left the extraction) and the live GL9xx
    finding count over the tree gomelint's CI invocation sweeps — both
    already FAIL CI through gomelint when they drift; the rows put the
    trend in every perf log, same split as the GL8xx pair."""
    try:
        from gome_tpu.analysis.surface import DEFAULT_UNIVERSE, load_universe

        committed = load_universe(os.path.join(ROOT, DEFAULT_UNIVERSE))
        gated = {
            "surface.combo_universe_log2": _universe_log2(),
        }
        advisory = {
            "surface.universe_entries": (
                len(committed["dimensions"]) if committed else 0
            ),
            "surface.gl9xx_findings": _family_findings("GL9"),
        }
        return gated, advisory
    except Exception as exc:  # pragma: no cover - env-specific
        return {}, {"surface.advisory_error": f"{type(exc).__name__}: {exc}"}


def collect() -> dict:
    """{"jax": version, "gated": {...}, "advisory": {...}}."""
    import jax

    from gome_tpu.obs import costmodel

    gated = dict(costmodel.ratchet_metrics("int32"))
    drill = frame_drill()
    gated.update(drill["gated"])
    advisory = drill["advisory"]
    advisory.update(skew_advisory())
    admit_gated, admit_advisory = gateway_gated()
    gated.update(admit_gated)
    advisory.update(admit_advisory)
    advisory.update(gateway_advisory())
    advisory.update(recovery_advisory())
    advisory.update(fleet_advisory())
    advisory.update(fleet_chaos_advisory())
    advisory.update(capacity_advisory())
    advisory.update(placement_advisory())
    advisory.update(sharding_advisory())
    surf_gated, surf_advisory = surface_metrics()
    gated.update(surf_gated)
    advisory.update(surf_advisory)
    return {
        "jax": jax.__version__,
        "gated": gated,
        "advisory": advisory,
    }


def gate(current: dict, baseline: dict) -> tuple[list[str], list[str]]:
    """(regressions, notes) against a loaded baseline document."""
    regressions: list[str] = []
    notes: list[str] = []
    base_metrics = baseline.get("metrics", {})
    tolerances = baseline.get("tolerance", {})
    version_match = baseline.get("jax") == current["jax"]
    if not version_match:
        notes.append(
            f"jax {current['jax']} != baseline jax {baseline.get('jax')}: "
            "XLA-derived metrics degraded to ADVISORY (re-baseline with "
            "--update-baseline after reviewing the new numbers); the "
            "compile count stays gated."
        )
    for name, cur in sorted(current["gated"].items()):
        base = base_metrics.get(name)
        if base is None:
            notes.append(
                f"new metric {name}={cur} not in baseline "
                "(run --update-baseline to start gating it)"
            )
            continue
        exact = name in EXACT_METRICS
        wallclock = name in WALLCLOCK_GATED
        if not exact and not wallclock and not version_match:
            continue  # XLA numbers are per-jaxlib; advisory on mismatch
        tol = 0.0 if exact else float(
            tolerances.get(name, tolerances.get("default",
                                               DEFAULT_TOLERANCE))
        )
        if name in HIGHER_BETTER:
            # Growth is the win; the gate is a FLOOR at base/(1+tol).
            limit = base / (1.0 + tol)
            if cur < limit - 1e-9:
                regressions.append(
                    f"{name}: {cur} < baseline {base} / (1+{tol:.0%}) "
                    f"= {limit:.1f} (higher is better)"
                )
            elif cur > base * (1.0 + tol) + 1e-9:
                notes.append(
                    f"{name} improved: {cur} > baseline {base} — "
                    "consider --update-baseline to lock in the win"
                )
            continue
        limit = base * (1.0 + tol)
        if cur > limit + 1e-9:
            regressions.append(
                f"{name}: {cur} > baseline {base} (+{tol:.0%} tolerance)"
            )
        elif cur < base * (1.0 - min(tol, 1.0)) - 1e-9:
            notes.append(
                f"{name} improved: {cur} < baseline {base} — consider "
                "--update-baseline to lock in the win"
            )
    for name in sorted(set(base_metrics) - set(current["gated"])):
        # A metric the baseline gates but the current run cannot produce
        # (backend stopped reporting it) must not pass silently.
        regressions.append(
            f"{name}: in baseline but absent from the current run"
        )
    return regressions, notes


def save_baseline(path: str, current: dict) -> None:
    doc = {
        "version": 1,
        "tool": "perf_ratchet",
        "jax": current["jax"],
        "note": (
            "Deterministic analytic device metrics (lower is better). CI "
            "fails when a gated metric grows past its tolerance. "
            "Regenerate with scripts/perf_ratchet.py --update-baseline; "
            "review the diff — shrinking is progress, growing is debt."
        ),
        "tolerance": {
            "default": DEFAULT_TOLERANCE,
            # Wall-clock admit rows gate with 3x headroom (see
            # WALLCLOCK_TOLERANCE): shared-runner noise passes, a
            # per-order-Python-loop regression (~7x) fails.
            **{name: WALLCLOCK_TOLERANCE for name in WALLCLOCK_GATED
               if name in current["gated"]},
        },
        "metrics": dict(sorted(current["gated"].items())),
        "advisory": dict(sorted(current["advisory"].items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="perf_ratchet", description=__doc__)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: PERF_BASELINE.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current metrics")
    ap.add_argument("--report", default="",
                    help="also write the full report JSON here")
    args = ap.parse_args(argv)

    try:
        current = collect()
    except Exception as exc:  # an unusable toolchain is an ERROR, not a pass
        print(f"perf_ratchet: metric collection failed: {exc}",
              file=sys.stderr)
        return 2

    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(current, fh, indent=2)
            fh.write("\n")

    if args.update_baseline:
        save_baseline(args.baseline, current)
        print(f"perf_ratchet: baseline rewritten "
              f"({len(current['gated'])} gated metrics) -> {args.baseline}")
        return 0

    try:
        with open(args.baseline, encoding="utf-8") as fh:
            baseline = json.load(fh)
    except OSError:
        print(
            f"perf_ratchet: no baseline at {args.baseline} — run with "
            "--update-baseline and commit the file",
            file=sys.stderr,
        )
        return 1

    regressions, notes = gate(current, baseline)
    for n in notes:
        print(f"# {n}")
    for a, v in sorted(current["advisory"].items()):
        print(f"# advisory {a} = {v}")
    admit_ns = current["gated"].get("gateway.admit_ns_per_order")
    admit_rate = current["gated"].get(
        "gateway.admit_orders_per_sec_per_core"
    )
    if admit_ns is not None:
        print(
            f"# GATED (wall-clock, 3x headroom): columnar admit path "
            f"measured at {admit_ns} ns/order -> {admit_rate} "
            "orders/sec/core (committed roofline: HOSTPROF_r02.json)"
        )
    scalar_ns = current["advisory"].get("gateway.scalar_admit_ns_per_order")
    scalar_rate = current["advisory"].get(
        "gateway.scalar_admit_orders_per_sec_per_core"
    )
    if scalar_ns is not None:
        print(
            f"# ADVISORY (never gated, wall-clock): scalar admit path "
            f"measured at {scalar_ns} ns/order -> {scalar_rate} "
            "orders/sec/core — the single-order DoOrder baseline the "
            "columnar front door replaced for batch traffic"
        )
    for key in SKEW_METRICS:
        v = current["advisory"].get(key)
        if v is not None and v > SKEW_TARGET:
            print(
                f"# WARNING (advisory, non-gating): {key} = {v} exceeds "
                f"the ROADMAP open-item-2 target {SKEW_TARGET} — "
                "skew-aware placement still pending"
            )
    rec_p50 = current["advisory"].get("recovery.p50_s")
    if rec_p50 is not None:
        print(
            f"# ADVISORY (never gated, wall-clock): crash recovery p50 "
            f"{rec_p50:.4f}s, WAL replay "
            f"{current['advisory'].get('recovery.wal_replay_frames_per_s')} "
            "frames/s across the committed chaos run (CHAOS_r01.json; "
            "regenerate with scripts/chaos.py)"
        )
    if current["advisory"].get("recovery.verdict_pass") is False:
        print(
            "# WARNING (advisory, non-gating): the committed chaos "
            "verdict has pass=false — tests/test_chaos.py should be "
            "failing; investigate before trusting recovery numbers"
        )
    fleet_rate = current["advisory"].get("fleet.orders_per_sec")
    if fleet_rate is not None:
        print(
            f"# ADVISORY (never gated, wall-clock): fleet aggregate "
            f"{fleet_rate} orders/sec over "
            f"{current['advisory'].get('fleet.partitions')} partitions, "
            f"stitched cross-process e2e p50 "
            f"{current['advisory'].get('fleet.stitched_p50_ms')} ms "
            "(FLEET_r01.json; regenerate with scripts/fleet_drill.py)"
        )
    if current["advisory"].get("fleet.verdict_pass") is False:
        print(
            "# WARNING (advisory, non-gating): the committed fleet "
            "verdict has pass=false — tests/test_fleet.py should be "
            "failing; investigate before trusting fleet numbers"
        )
    knee_rate = current["advisory"].get("capacity.knee_delivered_per_sec")
    if knee_rate is not None:
        print(
            f"# ADVISORY (never gated, wall-clock): fleet saturation "
            f"knee at {knee_rate} delivered orders/sec "
            f"(offered "
            f"{current['advisory'].get('capacity.knee_offered_per_sec')}"
            f"/s), corrected p99 at half-knee load "
            f"{current['advisory'].get('capacity.corrected_p99_ms_at_half_knee')}"
            f" ms, saturated stage: "
            f"{current['advisory'].get('capacity.saturated_stage')} "
            "(CAPACITY_r01.json; regenerate with scripts/capacity.py "
            "--fleet)"
        )
    if current["advisory"].get("capacity.verdict_pass") is False:
        print(
            "# WARNING (advisory, non-gating): the committed capacity "
            "verdict has pass=false — tests/test_capacity.py should be "
            "failing; investigate before trusting capacity numbers"
        )
    obs_skew = current["advisory"].get("placement.observed_shard_skew")
    best_skew = current["advisory"].get("placement.predicted_best_skew")
    if obs_skew is not None and best_skew:
        print(
            f"# ADVISORY (never gated, model-predicted): committed Zipf "
            f"flow top-16 share "
            f"{current['advisory'].get('placement.top16_share')}, "
            f"observed D=8 shard skew {obs_skew} vs predicted-best "
            f"{best_skew} under "
            f"{current['advisory'].get('placement.best_policy')} "
            "(PLACEMENT_r01.json; regenerate with "
            "scripts/placement_eval.py --out PLACEMENT_r01.json)"
        )
        if obs_skew / best_skew > 1.5:
            print(
                f"# WARNING (advisory, non-gating): observed shard skew "
                f"{obs_skew} is {obs_skew / best_skew:.2f}x the "
                f"predicted-best {best_skew} — the what-if evaluator "
                "says a committed policy would beat today's block "
                "placement by >1.5x; ROADMAP open item 2 is leaving "
                "real rows on the table"
            )
    if current["advisory"].get("placement.verdict_pass") is False:
        print(
            "# WARNING (advisory, non-gating): the committed placement "
            "verdict has pass=false — tests/test_placement.py should "
            "be failing; investigate before trusting placement numbers"
        )
    gl8 = current["advisory"].get("sharding.gl8xx_findings")
    if gl8 is not None and gl8 > 0:
        print(
            f"# WARNING (advisory, non-gating): {gl8} live GL8xx "
            "finding(s) in the tree — gomelint's analysis-job ratchet "
            "should be failing; fix or suppress with an owning "
            "workstream before trusting the sharding manifest"
        )
    gl9 = current["advisory"].get("surface.gl9xx_findings")
    if gl9 is not None and gl9 > 0:
        print(
            f"# WARNING (advisory, non-gating): {gl9} live GL9xx "
            "finding(s) in the tree — the compile-surface contract "
            "(combo-key agreement / quantizer lattice / precompile "
            "coverage) is violated and gomelint's analysis-job ratchet "
            "should be failing; fix before trusting the combo universe"
        )
    if regressions:
        print(f"perf_ratchet: {len(regressions)} regressed metric(s):")
        for r in regressions:
            print(f"  REGRESSION {r}")
        return 1
    print(
        f"perf_ratchet: OK — {len(current['gated'])} gated metrics within "
        "baseline"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

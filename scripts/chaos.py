#!/usr/bin/env python
"""Chaos soak: seeded kill/restart cycles with a machine-checked verdict.

The question this script answers: after N real process deaths injected at
the nastiest points we know (mid-frame, inside the at-least-once window,
torn sidecar writes, torn snapshot manifests), does recovery produce the
EXACT state and event stream an uninterrupted run produces?

Topology (everything file-backed, no gateway, no threads):

    parent                          worker child (this script, --worker)
    ------                          -----------------------------------
    record sim GCO frames  ──────>  doOrder FileQueue (pre-published)
    oracle child: clean run         boot -> Persister.restore_latest()
    kill cycle c = 1..N:            -> arm FAULTS from the cycle's plan
      write FaultPlan JSON          -> consume synchronously until the
      run child, expect exit 86        injected fault kills the process
    final child: clean run, exit 0     (exit EXIT_CODE) or queue drains
    compare: book digest,           -> MatchFeed.drain() + book digest
      match stream bytes,           -> result JSON (progressive write at
      seq audit, recovery p50/p99      WAL catch-up, full at completion)

Determinism: the worker is single-threaded (batch_n=1, per-message
commit), the fault registry is armed AFTER restore_latest() so a plan's
``at=(K,)`` indexes positions in THIS run's replay stream, and the sim
flow never reuses an (symbol, uuid, oid) key (flow.FlowState.next_oid is
monotonic) — so the recovery-time DEL-suppression refinement in
persist._reconstruct_marks cannot diverge replay from the oracle.

The verdict JSON (committed as CHAOS_r01.json, pinned by
tests/test_chaos.py) records the plans, per-cycle exit codes, recovery
times, the seq audit, and a pass/fail per check. CI runs this with
``--seconds 30 --kills 3`` and fails the build on any breach.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Must be set before anything imports jax (workers inherit it too).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gome_tpu.utils.faults import EXIT_CODE, FaultPlan, FaultSpec  # noqa: E402

SCHEMA = "gome-chaos-verdict-v1"

# Worker geometry: small enough to compile in seconds on CPU, matched to
# the sim flow below (n_slots >= n_lanes, max_t >= t_bins).
N_LANES = 16
T_BINS = 8
EVERY_N = 2  # snapshot cadence in committed batches (= messages here)
SNAP_KEEP = 8  # torn snapshots accumulate; keep enough good history


# -- shared by parent and worker --------------------------------------------

def build_engine():
    import jax.numpy as jnp

    from gome_tpu.engine.book import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine

    return MatchEngine(
        config=BookConfig(cap=64, max_fills=8, dtype=jnp.int64),
        n_slots=N_LANES,
        max_t=T_BINS,
        auto_grow=True,
        kernel="scan",
    )


def book_digest(engine) -> str:
    """sha256 over the full exported engine state (arrays bit-exact,
    interners, geometry) + the pre-pool — the bit-for-bit equality the
    chaos verdict asserts between oracle and recovered runs."""
    import numpy as np

    state = engine.batch.export_state()
    h = hashlib.sha256()
    for key in sorted(state):
        val = state[key]
        h.update(key.encode())
        if key == "books":
            for name in sorted(val):
                arr = np.ascontiguousarray(val[name])
                h.update(name.encode())
                h.update(str(arr.dtype).encode())
                h.update(repr(arr.shape).encode())
                h.update(arr.tobytes())
        else:
            h.update(repr(val).encode())
    h.update(repr(sorted(engine.pre_pool)).encode())
    return h.hexdigest()


# -- worker ------------------------------------------------------------------

def run_worker(args) -> int:
    """One consumer-process lifetime: boot, restore, (optionally) arm the
    fault plan, consume the order queue synchronously, drain the feed,
    digest the book. An injected fault hard-exits with EXIT_CODE before
    this function returns."""
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig, PersistConfig
    from gome_tpu.persist import Persister
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.matchfeed import MatchFeed
    from gome_tpu.utils.faults import FAULTS

    bus = make_bus(
        BusConfig(backend="file", dir=args.bus_dir, match_wire="frame")
    )
    engine = build_engine()
    persist = Persister(PersistConfig(
        enabled=True, dir=args.snap_dir, every_n_batches=EVERY_N,
        keep=SNAP_KEEP,
    ))
    # batch_n=1: one message per step, commit per message — fault hit
    # counters then index individual frames, reproducibly.
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0.0,
        on_batch=persist.on_batch, match_wire="frame",
    )
    feed = MatchFeed(bus, log_events=False)
    persist.attach(engine, bus, consumer=consumer)

    oq = bus.order_queue
    pre_committed = oq.committed()  # the crashed predecessor's position
    t0 = time.monotonic()
    persist.restore_latest()

    # Arm AFTER restore: restore-time sidecar writes must not consume
    # fault hits, so a plan's at=(K,) means "the K-th <point> of THIS
    # run" — reproducible from the verdict artifact alone.
    if args.plan:
        with open(args.plan) as f:
            FAULTS.install(FaultPlan.from_json(f.read()))

    result: dict = {
        "pre_committed": pre_committed,
        "restore": persist.probe(),
        "completed": False,
    }

    def write_result() -> None:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
        os.replace(tmp, args.out)

    # recovery_s = restore + WAL catch-up back to the pre-crash position,
    # cold process (includes the first dispatch's compile). Written as
    # soon as it is known so a later injected death cannot lose it.
    caught_up = oq.committed() >= pre_committed
    if caught_up:
        result["recovery_s"] = persist.last_recovery_seconds
        write_result()
    while oq.committed() < oq.end_offset():
        consumer.run_once()
        if not caught_up and oq.committed() >= pre_committed:
            caught_up = True
            result["recovery_s"] = time.monotonic() - t0
            write_result()
    feed.drain()
    result.update({
        "completed": True,
        "book_digest": book_digest(engine),
        "match_seq": consumer.match_seq,
        "feed": feed.seq_state(),
        "faults": FAULTS.report() if args.plan else None,
        "oq": {"end": oq.end_offset(), "committed": oq.committed()},
        "mq": {
            "end": bus.match_queue.end_offset(),
            "committed": bus.match_queue.committed(),
        },
    })
    write_result()
    return 0


# -- parent ------------------------------------------------------------------

def plan_for_cycle(cycle: int, seed: int) -> FaultPlan:
    """The kill rotation. Cycle 1 always dies inside the at-least-once
    window at offset 0 (match events published, NOTHING committed, no
    snapshot yet — the stale-match-tail case); later cycles rotate
    through the remaining fault classes at hit K, chosen past the replay
    window (<= EVERY_N messages) so every cycle makes net progress."""
    k = EVERY_N + 2 + ((cycle - 1) % 3)  # 4..6
    if cycle == 1:
        spec = FaultSpec("consumer.commit", mode="exit", at=(1,))
    else:
        rot = (cycle - 2) % 4
        if rot == 0:
            spec = FaultSpec("consumer.frame", mode="exit", at=(k,))
        elif rot == 1:
            spec = FaultSpec("filelog.offset", mode="torn", at=(k,))
        elif rot == 2:
            # 2nd snapshot of the run: published torn, then death —
            # load_latest must fall back to the previous snapshot.
            spec = FaultSpec("snapshot.rename", mode="torn", at=(2,))
        else:
            spec = FaultSpec("filelog.append", mode="torn", at=(k,))
    return FaultPlan(seed=seed * 1000 + cycle, faults=(spec,))


def record_sim_frames(seed: int, n_steps: int) -> list[bytes]:
    from gome_tpu.sim.env import EnvConfig
    from gome_tpu.sim.flow import FlowConfig
    from gome_tpu.sim.replay import record_frames

    # Dense enough that (a) no step is empty and (b) most frames publish
    # match events — the filelog.append fault point needs real appends.
    cfg = EnvConfig(flow=FlowConfig(
        n_lanes=N_LANES, t_bins=T_BINS, dt=0.07,
        submit_rate=3.0, cancel_rate=1.5, market_rate=1.0,
    ))
    return record_frames(cfg, seed, n_steps)


def seed_queue(bus_dir: str, frames: list[bytes]) -> None:
    from gome_tpu.bus.filelog import FileQueue

    q = FileQueue("doOrder", os.path.join(bus_dir, "doOrder"))
    for fr in frames:
        q.publish(fr)
    q.close()


def read_match_stream(bus_dir: str) -> tuple[list[bytes], list[int]]:
    """The durable queue-level record: every event as its canonical JSON
    line (seq included) plus the raw seq sequence for the audit."""
    from gome_tpu.bus.colwire import decode_event_frame
    from gome_tpu.bus.filelog import FileQueue

    q = FileQueue("matchOrder", os.path.join(bus_dir, "matchOrder"))
    lines: list[bytes] = []
    seqs: list[int] = []
    for m in q.read_from(0, q.end_offset()):
        batch = decode_event_frame(m.body)
        lines.extend(batch.to_json_lines())
        for r in batch.to_results():
            if r.seq is not None:
                seqs.append(r.seq)
    q.close()
    return lines, seqs


def audit_seqs(seqs: list[int]) -> dict:
    """Full-stream exactly-once audit (SeqTracker anchored at seq 0)."""
    from gome_tpu.service.matchfeed import SeqTracker

    tracker = SeqTracker(first_seq=0)
    for s in seqs:
        tracker.observe(s)
    return tracker.state()


def pctl(xs: list[float], p: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))]


def run_child(
    bus_dir: str, snap_dir: str, out: str, plan_path: str | None = None
) -> tuple[int, float]:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--bus-dir", bus_dir, "--snap-dir", snap_dir, "--out", out,
    ]
    if plan_path:
        cmd += ["--plan", plan_path]
    t0 = time.monotonic()
    proc = subprocess.run(cmd, timeout=300)
    return proc.returncode, time.monotonic() - t0


def read_result(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_parent(args) -> int:
    import tempfile

    work = args.workdir or tempfile.mkdtemp(prefix="gome-chaos-")
    os.makedirs(work, exist_ok=True)
    n_steps = max(16, min(160, max(args.seconds, 8 * args.kills)))
    print(f"chaos: recording {n_steps} sim steps (seed {args.seed})...")
    frames = record_sim_frames(args.seed, n_steps)
    from gome_tpu.bus.colwire import decode_order_frame

    n_orders = sum(int(decode_order_frame(fr)["n"]) for fr in frames)
    print(f"chaos: {len(frames)} frames / {n_orders} orders -> {work}")

    dirs = {}
    for run in ("oracle", "chaos"):
        dirs[run] = {
            "bus": os.path.join(work, run, "bus"),
            "snaps": os.path.join(work, run, "snaps"),
        }
        os.makedirs(dirs[run]["bus"], exist_ok=True)
        os.makedirs(dirs[run]["snaps"], exist_ok=True)
        seed_queue(dirs[run]["bus"], frames)

    # -- oracle: one uninterrupted run ----------------------------------
    oracle_out = os.path.join(work, "oracle_result.json")
    oracle_rc, oracle_wall = run_child(
        dirs["oracle"]["bus"], dirs["oracle"]["snaps"], oracle_out
    )
    oracle = read_result(oracle_out) or {}
    print(f"chaos: oracle rc={oracle_rc} wall={oracle_wall:.1f}s "
          f"digest={oracle.get('book_digest', '?')[:12]}...")

    # -- chaos: N killed cycles, then one clean run to completion -------
    cycles = []
    for c in range(1, args.kills + 1):
        plan = plan_for_cycle(c, args.seed)
        plan_path = os.path.join(work, f"plan_{c}.json")
        with open(plan_path, "w") as f:
            f.write(plan.to_json())
        out_c = os.path.join(work, f"chaos_result_{c}.json")
        rc, wall = run_child(
            dirs["chaos"]["bus"], dirs["chaos"]["snaps"], out_c, plan_path
        )
        res = read_result(out_c) or {}
        spec = plan.faults[0]
        print(f"chaos: cycle {c} [{spec.point}/{spec.mode}@{spec.at}] "
              f"rc={rc} wall={wall:.1f}s "
              f"recovery={res.get('recovery_s', -1):.3f}s")
        cycles.append({
            "cycle": c,
            "plan": plan.to_dict(),
            "exit_code": rc,
            "wall_s": round(wall, 3),
            "pre_committed": res.get("pre_committed"),
            "recovery_s": res.get("recovery_s"),
            "restore": res.get("restore"),
        })
    final_out = os.path.join(work, "chaos_result_final.json")
    final_rc, final_wall = run_child(
        dirs["chaos"]["bus"], dirs["chaos"]["snaps"], final_out
    )
    final = read_result(final_out) or {}
    print(f"chaos: final rc={final_rc} wall={final_wall:.1f}s "
          f"digest={final.get('book_digest', '?')[:12]}...")

    # -- verdict --------------------------------------------------------
    oracle_lines, oracle_seqs = read_match_stream(dirs["oracle"]["bus"])
    chaos_lines, chaos_seqs = read_match_stream(dirs["chaos"]["bus"])
    seq_audit = audit_seqs(chaos_seqs)
    oracle_audit = audit_seqs(oracle_seqs)

    # Recovery samples: every boot that followed an injected death
    # (cycles 2..N and the final run). Cycle 1 boots fresh.
    recoveries = [
        c["recovery_s"] for c in cycles[1:] if c["recovery_s"] is not None
    ]
    if final.get("recovery_s") is not None:
        recoveries.append(final["recovery_s"])
    wal_frames = sum(
        (c["restore"] or {}).get("wal_replay_frames", 0) for c in cycles[1:]
    ) + (final.get("restore") or {}).get("wal_replay_frames", 0)
    total_rec = sum(recoveries)

    feed_state = final.get("feed") or {}
    checks = {
        "oracle_clean_exit": oracle_rc == 0,
        "all_kills_injected": all(
            c["exit_code"] == EXIT_CODE for c in cycles
        ),
        "final_clean_exit": final_rc == 0,
        "book_digest_match": (
            bool(oracle.get("book_digest"))
            and oracle.get("book_digest") == final.get("book_digest")
        ),
        "match_stream_identical": (
            len(oracle_lines) > 0 and oracle_lines == chaos_lines
        ),
        "queue_seq_no_dupes": seq_audit["dupes"] == 0,
        "queue_seq_no_gaps": seq_audit["gaps"] == 0,
        "feed_exactly_once": (
            feed_state.get("dupes") == 0 and feed_state.get("gaps") == 0
        ),
        "recovery_measured": len(recoveries) >= args.kills,
    }
    verdict = {
        "schema": SCHEMA,
        "config": {
            "seed": args.seed,
            "seconds": args.seconds,
            "kills": args.kills,
            "n_steps": n_steps,
            "frames": len(frames),
            "orders": n_orders,
            "every_n_batches": EVERY_N,
            "engine": {
                "n_slots": N_LANES, "max_t": T_BINS,
                "cap": 64, "max_fills": 8, "dtype": "int64",
            },
        },
        "oracle": {
            "exit_code": oracle_rc,
            "wall_s": round(oracle_wall, 3),
            "book_digest": oracle.get("book_digest"),
            "events": len(oracle_lines),
            "match_seq": oracle.get("match_seq"),
            "seq_audit": oracle_audit,
        },
        "cycles": cycles,
        "final": {
            "exit_code": final_rc,
            "wall_s": round(final_wall, 3),
            "book_digest": final.get("book_digest"),
            "events": len(chaos_lines),
            "match_seq": final.get("match_seq"),
            "feed": feed_state,
        },
        "matchfeed": {
            "events": len(chaos_lines),
            "stamped": len(chaos_seqs),
            "seq_audit": seq_audit,
        },
        "recovery": {
            "samples_s": [round(r, 4) for r in recoveries],
            "p50_s": pctl(recoveries, 50),
            "p99_s": pctl(recoveries, 99),
            "wal_replay_frames_total": wal_frames,
            "wal_replay_frames_per_s": (
                round(wal_frames / total_rec, 2) if total_rec > 0 else None
            ),
        },
        "checks": checks,
        "pass": all(checks.values()),
    }
    with open(args.out, "w") as f:
        json.dump(verdict, f, indent=1, sort_keys=True)
        f.write("\n")
    status = "PASS" if verdict["pass"] else "FAIL"
    print(f"chaos: {status} -> {args.out}")
    for name, ok in checks.items():
        print(f"  [{'ok' if ok else 'BREACH'}] {name}")
    return 0 if verdict["pass"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seconds", type=int, default=30,
                    help="soak scale knob: sim steps to record (clamped)")
    ap.add_argument("--kills", type=int, default=3,
                    help="injected process deaths before the clean run")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default="CHAOS_r01.json",
                    help="verdict JSON path (parent mode)")
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: fresh tempdir)")
    # worker mode (internal)
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--bus-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--snap-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--plan", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.worker:
        return run_worker(args)
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""gomelint — run the domain-specific static analyzers over the tree.

    python scripts/gomelint.py gome_tpu                 # AST rules
    python scripts/gomelint.py gome_tpu --jaxpr         # + jaxpr audits
    python scripts/gomelint.py gome_tpu --select GL5    # one family
    python scripts/gomelint.py gome_tpu --format sarif  # review annotations
    python scripts/gomelint.py gome_tpu --update-baseline
    python scripts/gomelint.py --list-rules

Exit status: 0 when every finding is clean or baselined, 1 when any NEW
(non-baselined) finding survives suppressions, 2 on usage errors. The
baseline (gome_tpu/analysis/baseline.json, override with --baseline,
disable with --no-baseline) is the ratchet: existing debt is recorded by
content-addressed fingerprint, new debt fails. `--report FILE` writes
findings JSON and `--sarif FILE` writes SARIF 2.1.0 (both uploaded by the
CI analysis job; SARIF renders as code-review annotations).

The AST rules are dependency-free; `--jaxpr` imports jax and traces the
engine's device entry points ONCE (a few seconds on CPU), feeding the
GL2xx dtype-envelope audit, the GL6xx buffer-donation audit, AND the
GL8xx sharding-manifest ratchet (GL806) from the same traced jaxprs —
see gome_tpu/analysis/envelope.py, gome_tpu/analysis/donation.py,
gome_tpu/analysis/sharding.py, and ARCHITECTURE.md "Static analysis".
`--update-manifest` (with --jaxpr) rewrites the committed sharding
manifest (gome_tpu/analysis/shard_manifest.json, override with
--manifest) to the current spec surface; like --update-baseline, the
diff is reviewed, not silently absorbed.

The GL9xx compile-surface family (gome_tpu/analysis/surface.py) splits
three ways: GL901-GL904 are AST rules that ride the normal run; GL905
(combo-universe drift vs gome_tpu/analysis/combo_universe.json, override
with --universe, regenerate with `--jaxpr --update-universe`) shares the
--jaxpr engine import; and `--journal FILE` runs the GL906 runtime-escape
check — a compile-journal export (soak/chaos/obs_snapshot artifact)
checked combo-by-combo against the COMMITTED universe, pure JSON, no
--jaxpr needed.
CI's dedicated race job re-runs `--select GL7` (the thread-escape
family, AST-only, so thread-discipline regressions are named by rule)
before the scripts/race_drill.py lockset drill.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from gome_tpu.analysis import rule_catalogue, run_paths  # noqa: E402
from gome_tpu.analysis.baseline import (  # noqa: E402
    DEFAULT_BASELINE,
    fingerprint_findings,
    load_baseline,
    partition,
    save_baseline,
)
from gome_tpu.analysis.core import (  # noqa: E402
    TOOL_VERSION,
    _ensure_checkers_loaded,
)
from gome_tpu.analysis.sharding import DEFAULT_MANIFEST  # noqa: E402
from gome_tpu.analysis.surface import DEFAULT_UNIVERSE  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="gomelint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files or directories")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids/prefixes (GL1,GL402,...)")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also run the traced-engine audits: GL2xx "
                         "dtype envelope + GL6xx buffer donation")
    ap.add_argument("--dtype", default="int32", choices=("int32", "int64"),
                    help="declared book dtype for the jaxpr audits")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"))
    ap.add_argument("--report", default="",
                    help="write findings as JSON to this path")
    ap.add_argument("--sarif", default="",
                    help="write findings as SARIF 2.1.0 to this path")
    ap.add_argument("--baseline", default=os.path.join(ROOT, DEFAULT_BASELINE),
                    help="baseline file for the ratchet (default: "
                         f"{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding fails")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "and exit 0 (review the diff!)")
    ap.add_argument("--manifest",
                    default=os.path.join(ROOT, DEFAULT_MANIFEST),
                    help="sharding manifest for the GL806 drift ratchet "
                         f"(default: {DEFAULT_MANIFEST})")
    ap.add_argument("--update-manifest", action="store_true",
                    help="(with --jaxpr) rewrite the sharding manifest "
                         "to the current spec surface and exit 0 "
                         "(review the diff!)")
    ap.add_argument("--universe",
                    default=os.path.join(ROOT, DEFAULT_UNIVERSE),
                    help="combo-universe manifest for the GL905 drift "
                         f"ratchet (default: {DEFAULT_UNIVERSE})")
    ap.add_argument("--update-universe", action="store_true",
                    help="(with --jaxpr) rewrite the combo universe to "
                         "the current engine bounds and exit 0 "
                         "(review the diff!)")
    ap.add_argument("--journal", default="",
                    help="compile-journal export (JSON) to check against "
                         "the committed combo universe (GL906; no "
                         "--jaxpr needed)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include findings silenced by gomelint directives")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--version", action="version",
                    version=f"gomelint {TOOL_VERSION}")
    args = ap.parse_args(argv)

    if args.list_rules:
        _ensure_checkers_loaded()
        from gome_tpu.analysis import envelope  # noqa: F401 - registers GL2xx
        for rule, desc in rule_catalogue().items():
            print(f"{rule}  {desc}")
        return 0
    if not args.paths:
        ap.error("no paths given (or use --list-rules)")
    if args.update_manifest and not args.jaxpr:
        ap.error("--update-manifest requires --jaxpr (the manifest "
                 "derives from the shared engine trace)")
    if args.update_universe and not args.jaxpr:
        ap.error("--update-universe requires --jaxpr (the universe "
                 "derives from the engine's config bounds)")

    select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
    findings = run_paths(args.paths, select or None,
                         keep_suppressed=args.show_suppressed)
    if args.jaxpr:
        # One shared trace (envelope.traced_entries memo) feeds both
        # jaxpr-driven families — GL2 and GL6 cost one engine trace total.
        from gome_tpu.analysis.core import apply_file_suppressions
        traced: list = []
        if not select or any(s.startswith("GL2") for s in select):
            from gome_tpu.analysis.envelope import check_engine_envelope
            traced.extend(check_engine_envelope(args.dtype))
        if not select or any(s.startswith("GL6") for s in select):
            from gome_tpu.analysis.donation import check_engine_donation
            traced.extend(check_engine_donation(args.dtype))
        if not select or any(s.startswith("GL8") for s in select):
            from gome_tpu.analysis.sharding import (
                check_sharding_manifest,
                extract_manifest,
                save_manifest,
            )
            if args.update_manifest:
                manifest = extract_manifest(args.dtype)
                save_manifest(args.manifest, manifest)
                print(f"gomelint: sharding manifest updated with "
                      f"{len(manifest['entries'])} entr(ies) -> "
                      f"{args.manifest}")
                return 0
            traced.extend(check_sharding_manifest(args.dtype,
                                                  args.manifest))
        if not select or any(s.startswith("GL9") for s in select):
            from gome_tpu.analysis.surface import (
                check_universe,
                extract_universe,
                save_universe,
            )
            if args.update_universe:
                universe = extract_universe()
                save_universe(args.universe, universe)
                print(f"gomelint: combo universe updated with "
                      f"{len(universe['dimensions'])} dimension(s) -> "
                      f"{args.universe}")
                return 0
            traced.extend(check_universe(args.universe))
        if not args.show_suppressed:
            traced = apply_file_suppressions(traced, root=ROOT)
        findings.extend(traced)
    if args.journal and (not select
                         or any(s.startswith("GL9") for s in select)):
        from gome_tpu.analysis.surface import check_journal_escape
        findings.extend(check_journal_escape(args.journal, args.universe))

    fingerprinted = fingerprint_findings(findings, root=ROOT)
    if args.update_baseline:
        save_baseline(args.baseline, fingerprinted)
        print(f"gomelint: baseline updated with {len(findings)} "
              f"finding(s) -> {args.baseline}")
        return 0
    base = {} if args.no_baseline else load_baseline(args.baseline)
    new, known = partition(fingerprinted, base)

    payload = [
        dict(f.__dict__, fingerprint=fp, baselined=fp in base)
        for f, fp in fingerprinted
    ]
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(
                {"findings": payload, "count": len(findings),
                 "new": len(new), "baselined": len(known)},
                fh, indent=2,
            )
    sarif_doc = None
    if args.sarif or args.format == "sarif":
        from gome_tpu.analysis.sarif import to_sarif
        sarif_doc = to_sarif(fingerprinted, baselined=set(base), root=ROOT)
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(sarif_doc, fh, indent=2)

    if args.format == "json":
        print(json.dumps(payload, indent=2))
    elif args.format == "sarif":
        print(json.dumps(sarif_doc, indent=2))
    else:
        for f, fp in fingerprinted:
            tag = " [baselined]" if fp in base else ""
            print(f.format() + tag)
        summary = f"gomelint: {len(findings)} finding(s)"
        if known:
            summary += f" ({len(known)} baselined, {len(new)} new)"
        print(summary)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""gomelint — run the domain-specific static analyzers over the tree.

    python scripts/gomelint.py gome_tpu                 # AST rules
    python scripts/gomelint.py gome_tpu --jaxpr         # + jaxpr envelope
    python scripts/gomelint.py gome_tpu --select GL4    # one family
    python scripts/gomelint.py --list-rules

Exit status: 0 when clean, 1 when any finding survives suppressions,
2 on usage errors. `--report FILE` writes the findings as JSON (the CI
analysis job uploads it as an artifact). The AST rules are dependency-
free; `--jaxpr` imports jax and traces the engine's device entry points
(a few seconds on CPU), auditing every intermediate value's dtype against
the declared book envelope — see gome_tpu/analysis/envelope.py and the
"Static analysis" section of ARCHITECTURE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gome_tpu.analysis import rule_catalogue, run_paths  # noqa: E402
from gome_tpu.analysis.core import _ensure_checkers_loaded  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="gomelint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files or directories")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids/prefixes (GL1,GL402,...)")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also run the jaxpr int32-envelope audit (GL2xx)")
    ap.add_argument("--dtype", default="int32", choices=("int32", "int64"),
                    help="declared book dtype for the envelope audit")
    ap.add_argument("--format", default="text", choices=("text", "json"))
    ap.add_argument("--report", default="",
                    help="write findings as JSON to this path")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include findings silenced by gomelint directives")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        _ensure_checkers_loaded()
        from gome_tpu.analysis import envelope  # noqa: F401 - registers GL2xx
        for rule, desc in rule_catalogue().items():
            print(f"{rule}  {desc}")
        return 0
    if not args.paths:
        ap.error("no paths given (or use --list-rules)")

    select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
    findings = run_paths(args.paths, select or None,
                         keep_suppressed=args.show_suppressed)
    if args.jaxpr and (not select or any(s.startswith("GL2") for s in select)):
        from gome_tpu.analysis.envelope import check_engine_envelope
        findings.extend(check_engine_envelope(args.dtype))

    payload = [f.__dict__ for f in findings]
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump({"findings": payload, "count": len(findings)}, fh,
                      indent=2)
    if args.format == "json":
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(f"gomelint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""Measure the marker-store admission path at frame rate (VERDICT r4 #6).

The production single-binary topology keeps pre-pool markers IN-PROCESS
(the C++ open-addressing pool, engine/prepool.NativePrePool) — the
gateway marks on accept, the consumer consumes at admission, no network
hop. This probe times both halves on real frame-shaped columns (the
service bench's own mixed-flow shape: dictionary-encoded symbols/uuids,
fresh oids, ~45% DELs) and prints one JSON line of orders/sec/core —
the number that must clear a 0.5M/s shard's admission budget.

For SPLIT deployments (gateway and consumer in different processes) the
markers live in a RESP server instead; that slower path is measured
separately by the service bench's marker_server section and is not the
production shard topology.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
from gome_tpu.engine.prepool import NativePrePool, make_prepool

N = int(os.environ.get("PREPOOL_ORDERS", 262_144))
FRAMES = int(os.environ.get("PREPOOL_FRAMES", 8))
S = 10_240

flow = bench._MixedFlow(np.random.default_rng(11), S)
symbols = [f"sym{i}" for i in range(S)]
frames = [
    dict(flow.frame(N), symbols=symbols, uuids=bench._SVC_UUIDS)
    for _ in range(FRAMES)
]

pool = make_prepool()
native = isinstance(pool, NativePrePool)
if not hasattr(pool, "mark_frame"):
    # No native pool on this host (no C++ toolchain): the probe measures
    # the production admission path, which is the native pool — report
    # and exit instead of crashing on the bare-set fallback.
    print(json.dumps({"backend": "unavailable (no native prepool)"}))
    sys.exit(0)

# Warm (hash growth, interning) off the clock.
pool.mark_frame(frames[0])
pool.consume_frame(frames[0])

t0 = time.process_time()
for cols in frames:
    pool.mark_frame(cols)
mark_cpu = time.process_time() - t0

t0 = time.process_time()
total = 0
for cols in frames:
    keep, consumed = pool.consume_frame(cols)
    total += int(cols["n"])
consume_cpu = time.process_time() - t0

result = {
    "metric": "in-process pre-pool admission (mixed-flow frames, "
    f"{N}-order, {S} symbols)",
    "backend": "native-cc" if native else "python-set",
    "mark_orders_per_sec_per_core": round(N * FRAMES / max(mark_cpu, 1e-9)),
    "consume_orders_per_sec_per_core": round(total / max(consume_cpu, 1e-9)),
}
print(json.dumps(result))

#!/usr/bin/env python
"""Fleet chaos soak: seeded kill/restart cycles against a LIVE 2x2 fleet
with a machine-checked fault-tolerance verdict.

scripts/chaos.py answers "does one consumer recover bit-exactly?" against
a pre-seeded queue with no service in the loop. This script answers the
fleet-level question: when real processes die UNDER LIVE gRPC DRIVE —
a consumer killed mid-frame, a gateway killed mid-admit, the bus
"disconnected" under the gateway's feet — does the deployment as a whole
keep the invariants it advertises?

    - every death is an injected one (exit code 86, nothing else dies),
    - clients never lose an entry: gateway deaths are resubmitted
      duplicate-free (gateway.emit fires PRE-publish, so a killed chunk
      was never half-published), bus disconnects surface as the
      retryable status and the driver's backoff path absorbs them,
    - each partition's final book is BIT-EXACT against an uninterrupted
      oracle replay of the same order log (scripts/chaos.py --worker is
      the oracle: same consumer code, same engine geometry),
    - the fleet-wide match stream is exactly-once (per-partition seq
      audit anchored at first_seq=0, zero dupes, zero gaps),
    - recovery is bounded (p99 over all death->caught-up measurements),
    - aggregate accept throughput while a member is down stays above a
      floor (the degraded-window rate vs FLEET_r01's 410 orders/sec),
    - consumer failover rides the round-12 router tier: the dead
      member's partitions are reassigned (PartitionMap epoch bump via
      FailoverController) only AFTER the standby's durable-state
      recovery (Persister.restore_latest + WAL catch-up) completes.

Topology (parent drives everything; 4 long-lived children + respawns):

    parent                              children (this script, --worker)
    ------                              -----------------------------
    record sim GCO frames               gw0, gw1: OrderGateway + gRPC
    route via fleet.partition_of            (+ admission controller,
    drive rounds of namespaced               gateway.emit fault point)
      DoOrderBatch chunks, retrying    c0, c1: consumer + Persister +
      transport errors + code 14           MatchFeed over the partition
    kill cycles: rotate fault class        file bus (snapshots + WAL)
    failover via fleet router           oracle per partition:
    verdict -> FLEET_CHAOS_r01.json        scripts/chaos.py --worker

Kill rotation (cycle c, 1-indexed): fault class cycles through
consumer-kill / gateway-kill / bus-disconnect, victim partition
alternates. Faults are armed by restarting the victim with a FaultPlan
(the restart itself is part of the soak); `at=(K,)` counts events of
THAT lifetime, so the schedule is pinned in the verdict artifact.

The drive is paced rounds of the recorded sim flow with a per-round oid
namespace (keys never collide, cancels stay paired with their round's
adds), so the oracle needs no request list: it replays whatever the
gateways durably published. The verdict JSON (committed as
FLEET_CHAOS_r01.json, pinned by tests/test_fleet_chaos.py) records the
plans, per-cycle recovery, the degraded-window throughput table, the
router failover history, and a pass/fail per check. CI runs this with
``--seconds 30 --kills 3`` and fails the build on any breach.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import threading
import time

SCRIPTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(SCRIPTS)
sys.path.insert(0, REPO)
sys.path.insert(0, SCRIPTS)

# Must be set before anything imports jax (workers inherit it too).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gome_tpu.utils.faults import EXIT_CODE, FaultPlan, FaultSpec  # noqa: E402

from chaos import (  # noqa: E402 — shared machinery (scripts/chaos.py)
    audit_seqs, book_digest, build_engine, pctl, read_match_stream,
)
from fleet_drill import (  # noqa: E402 — fleet topology machinery
    N_PARTITIONS, Worker, record_sim_frames, requests_from_frames,
    rusage_self, start_respserver, write_json,
)

SCHEMA = "gome-fleet-chaos-verdict-v1"

CLASSES = ("consumer-kill", "gateway-kill", "bus-disconnect")

#: Orders per DoOrderBatch chunk = N_LANES * T_BINS: one engine dispatch
#: per published frame, so fault-hit counters index whole frames.
DRIVE_CHUNK = 128
#: Pause between chunks: paces the drive to ~400 orders/sec/partition so
#: a degraded window holds live traffic without drowning the consumers.
PACE_S = 0.3
#: Event index (per victim lifetime) at which the armed fault fires.
HIT_K = 3
EVERY_N = 8  # snapshot cadence in committed consumer batches
SNAP_KEEP = 16

CODE_RETRYABLE = 14  # service.gateway.CODE_RETRYABLE
RETRY_AFTER_RE = re.compile(r"retry-after=([0-9.]+)s")


# -- workers -----------------------------------------------------------------
#
# Same protocol as fleet_drill workers: one "READY ops=<p> grpc=<p>" line
# on stdout once serving, then block on stdin; any line (or EOF) is the
# stop signal. Injected exit-mode faults hard-exit with EXIT_CODE first.


def _await_stop() -> None:
    try:
        sys.stdin.readline()
    except Exception:
        pass


def run_gateway_worker(args) -> int:
    """One partition's front door: OrderGateway + admission controller
    over the partition file bus. Arms the cycle's FaultPlan (if any)
    and registers the "disconnect" call-handler: a gateway.emit hit in
    call mode raises ConnectionError PRE-publish, which the batch funnel
    converts to CODE_RETRYABLE with accepted=0 — the client's retry path
    absorbs it with zero loss and zero duplicates."""
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig, Config, GrpcConfig
    from gome_tpu.engine.prepool import RespPrePool, make_marker
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.service.admission import AdmissionController
    from gome_tpu.service.gateway import OrderGateway, serve_gateway
    from gome_tpu.utils.faults import FAULTS

    bus = make_bus(
        BusConfig(backend="file", dir=args.bus_dir, match_wire="frame")
    )

    def _disconnect() -> None:
        raise ConnectionError("injected bus disconnect (fleet_chaos)")

    FAULTS.handler("disconnect", _disconnect)
    if args.plan:
        with open(args.plan) as f:
            FAULTS.install(FaultPlan.from_json(f.read()))
    admission = AdmissionController(
        bus.order_queue.depth, max_depth=args.max_depth
    )
    # Split-process marker store: marks must land in the partition's RESP
    # server BEFORE publish, or the consumer's admission drops the ADDs as
    # unmarked (engine/orchestrator pre-pool contract).
    pool = RespPrePool(RespClient(port=args.resp_port))
    gateway = OrderGateway(
        bus, accuracy=0, mark=make_marker(pool), admission=admission,
        mark_frame=pool.mark_frame, unmark_frame=pool.unmark_frame,
    )
    server = serve_gateway(
        gateway, Config(grpc=GrpcConfig(host="127.0.0.1", port=0))
    )
    print(f"READY ops=0 grpc={server.bound_port}", flush=True)
    _await_stop()
    result = {
        "role": "gateway",
        "partition": args.partition,
        "published": {"doOrder": bus.order_queue.end_offset()},
        "faults": FAULTS.report() if args.plan else None,
        "rusage": rusage_self(),
    }
    write_json(args.result, result)
    server.stop(grace=1).wait()
    return 0


def run_consumer_worker(args) -> int:
    """One partition's engine half for one process lifetime: restore
    durable state, (optionally) arm the cycle's FaultPlan, then consume
    live under the threaded consumer until told to stop. The graceful
    final lifetime writes the book digest the oracle comparison pins."""
    from gome_tpu.bus import make_bus
    from gome_tpu.config import BusConfig, PersistConfig
    from gome_tpu.persist import Persister
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.matchfeed import MatchFeed
    from gome_tpu.utils.faults import FAULTS

    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient

    bus = make_bus(
        BusConfig(backend="file", dir=args.bus_dir, match_wire="frame")
    )
    engine = build_engine()
    # Same RESP store the partition's gateway marks into: consumption at
    # admission is the cross-process half of the exactly-once contract.
    # Assigned before attach/restore — restore_latest() rebuilds marks
    # into this pool in place (clear + update + WAL-tail reconstruct).
    engine.pre_pool = RespPrePool(RespClient(port=args.resp_port))
    persist = Persister(PersistConfig(
        enabled=True, dir=args.snap_dir, every_n_batches=EVERY_N,
        keep=SNAP_KEEP,
    ))
    consumer = OrderConsumer(
        engine, bus, batch_n=4, batch_wait_s=0.02,
        on_batch=persist.on_batch, match_wire="frame",
    )
    feed = MatchFeed(bus, log_events=False)
    persist.attach(engine, bus, consumer=consumer)
    pre_committed = bus.order_queue.committed()
    persist.restore_latest()
    # Arm AFTER restore (chaos.py discipline): restore-time replay must
    # not consume fault hits, so at=(K,) indexes the K-th frame THIS
    # lifetime consumes live.
    if args.plan:
        with open(args.plan) as f:
            FAULTS.install(FaultPlan.from_json(f.read()))
    consumer.start()
    feed.start()
    print("READY ops=0 grpc=0", flush=True)
    _await_stop()
    consumer.stop()
    consumer.drain()  # any frames between the last poll and the stop
    feed.stop()
    feed.drain()
    oq, mq = bus.order_queue, bus.match_queue
    result = {
        "role": "consumer",
        "partition": args.partition,
        "pre_committed": pre_committed,
        "restore": persist.probe(),
        "book_digest": book_digest(engine),
        "match_seq": consumer.match_seq,
        "feed": feed.seq_state(),
        "faults": FAULTS.report() if args.plan else None,
        "oq": {"end": oq.end_offset(), "committed": oq.committed()},
        "mq": {"end": mq.end_offset(), "committed": mq.committed()},
        "rusage": rusage_self(),
    }
    write_json(args.result, result)
    return 0


# -- parent: fault plans -----------------------------------------------------


def class_for_cycle(cycle: int) -> tuple[str, int]:
    """(fault class, victim partition) for 1-indexed cycle: the class
    rotates through all three, the partition alternates."""
    return CLASSES[(cycle - 1) % 3], (cycle - 1) % N_PARTITIONS


def plan_for_cycle(cycle: int, seed: int, klass: str) -> FaultPlan:
    if klass == "consumer-kill":
        spec = FaultSpec("consumer.frame", mode="exit", at=(HIT_K,))
    elif klass == "gateway-kill":
        spec = FaultSpec("gateway.emit", mode="exit", at=(HIT_K,))
    else:  # bus-disconnect: three consecutive emit attempts fail soft
        spec = FaultSpec(
            "gateway.emit", mode="call", handler="disconnect",
            at=(HIT_K, HIT_K + 1, HIT_K + 2),
        )
    return FaultPlan(seed=seed * 1000 + cycle, faults=(spec,))


# -- parent: chaos-aware drive -----------------------------------------------


class DriveCtl:
    """Shared state between the parent and the per-partition driver
    threads: live gateway targets (the parent repoints a partition after
    a restart), per-partition tallies, and timestamped cumulative-accept
    samples for degraded-window throughput."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.targets: dict[int, str] = {}
        # Health-gated shedding, parent-side: while a partition's member is
        # down its driver parks between chunks (the router tier would shed
        # RouteUnavailable; the drill sheds at the source). `idle[p]` acks
        # that no chunk is in flight — the standby's restore can then
        # rebuild the shared mark store without racing live marking.
        self.pause = {p: threading.Event() for p in range(N_PARTITIONS)}
        self.idle = {p: threading.Event() for p in range(N_PARTITIONS)}
        self.stats = {
            p: {
                "accepted": 0, "rejected": 0, "aborted": 0,
                "transport_retries": 0, "shed_retries": 0,
                "disconnect_retries": 0,
            }
            for p in range(N_PARTITIONS)
        }
        # [(monotonic_t, cumulative_accepted)]  guarded by self.lock
        self.samples: dict[int, list] = {p: [] for p in range(N_PARTITIONS)}

    def stat(self, p: int, key: str) -> int:
        with self.lock:
            return self.stats[p][key]


def _ns_requests(base: list, ns: str) -> list:
    """Re-key one round of the recorded flow under a fresh oid namespace:
    (symbol, uuid, oid) keys never collide across rounds, and cancels
    stay paired with their own round's adds (both get the prefix)."""
    from gome_tpu.api import order_pb2 as pb

    out = []
    for is_cancel, r in base:
        q = pb.OrderRequest()
        q.CopyFrom(r)
        q.oid = f"{ns}.{r.oid}"
        out.append((is_cancel, q))
    return out


def _send_chunk(ctl: DriveCtl, p: int, chunk: list) -> None:
    """Deliver one chunk come what may: transport errors mean the
    gateway is down or restarting — the in-flight batch was NOT
    published (gateway.emit fires pre-publish), so resubmitting the
    whole chunk to the restarted gateway is duplicate-free. CODE_RETRYABLE
    means shed or disconnected: resubmit the unconsumed tail after the
    server's retry-after hint (the round-12 remainder contract)."""
    import grpc

    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.api.service import OrderStub

    while chunk:
        target = ctl.targets[p]
        breq = pb.OrderBatchRequest(
            orders=[r for _, r in chunk],
            cancel=[c for c, _ in chunk],
        )
        try:
            with grpc.insecure_channel(target) as channel:
                resp = OrderStub(channel).DoOrderBatch(breq, timeout=30)
        except grpc.RpcError:
            with ctl.lock:
                ctl.stats[p]["transport_retries"] += 1
            time.sleep(0.25)
            continue
        # Consumed prefix contract: every entry before an abort point was
        # either accepted or per-entry rejected (clients/doorder.py).
        consumed = resp.accepted + len(resp.reject_index)
        with ctl.lock:
            st = ctl.stats[p]
            st["accepted"] += resp.accepted
            st["rejected"] += len(resp.reject_index)
            ctl.samples[p].append((time.monotonic(), st["accepted"]))
        if resp.code == CODE_RETRYABLE:
            msg = resp.message or ""
            key = (
                "disconnect_retries" if "batch aborted" in msg
                else "shed_retries"
            )
            with ctl.lock:
                ctl.stats[p][key] += 1
            chunk = chunk[consumed:]
            m = RETRY_AFTER_RE.search(msg)
            time.sleep(max(float(m.group(1)) if m else 0.0, 0.2))
            continue
        if consumed < len(chunk):  # permanent abort: count, don't hide
            with ctl.lock:
                ctl.stats[p]["aborted"] += len(chunk) - consumed
        return


def _drive_partition(
    ctl: DriveCtl, p: int, base: list, phase: str, done: threading.Event,
    min_rounds: int,
) -> None:
    r = 0
    while r < min_rounds or not done.is_set():
        reqs = _ns_requests(base, f"{phase}.r{r}")
        for i in range(0, len(reqs), DRIVE_CHUNK):
            if ctl.pause[p].is_set():
                ctl.idle[p].set()
                while ctl.pause[p].is_set() and not done.is_set():
                    time.sleep(0.05)
                ctl.idle[p].clear()
            _send_chunk(ctl, p, reqs[i : i + DRIVE_CHUNK])
            time.sleep(PACE_S)
        r += 1


def drive_burst(
    ctl: DriveCtl, parts: list, phase: str, done: threading.Event,
    min_rounds: int = 1,
) -> list:
    threads = [
        threading.Thread(
            target=_drive_partition,
            args=(ctl, p, parts[p], phase, done, min_rounds),
            daemon=True,
        )
        for p in range(N_PARTITIONS)
    ]
    for t in threads:
        t.start()
    return threads


def window_rate(ctl: DriveCtl, t0: float, t1: float) -> dict:
    """Aggregate fleet accept throughput inside [t0, t1] from the
    cumulative samples (nearest sample at or before each edge)."""
    total = 0
    with ctl.lock:
        samples = {p: list(ctl.samples[p]) for p in range(N_PARTITIONS)}
    for p in range(N_PARTITIONS):
        a0 = a1 = 0
        for t, a in samples[p]:
            if t <= t0:
                a0 = a
            if t <= t1:
                a1 = a
            else:
                break
        total += a1 - a0
    dur = max(1e-9, t1 - t0)
    return {
        "orders": total,
        "window_s": round(t1 - t0, 3),
        "orders_per_s": round(total / dur, 1),
    }


# -- parent: durable-offset polling (sidecar reads, never FileQueue opens:
# opening a live queue from a second process could truncate a mid-append
# tail the writer is still fsyncing) --------------------------------------

_OFF_RE = re.compile(rb"\s*(\d+)")


def log_end(bus_dir: str) -> int:
    """Record count of the order log — the same unit the committed
    sidecar carries (FileQueue offsets are record indexes). Walks the
    4-byte-BE length prefixes; an incomplete tail record (live writer
    mid-append) is not counted, matching FileQueue's own tail rule."""
    path = os.path.join(bus_dir, "doOrder.log")
    n = 0
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            pos = 0
            while pos + 4 <= size:
                ln = int.from_bytes(f.read(4), "big")
                if pos + 4 + ln > size:
                    break  # torn/live tail: not yet a record
                f.seek(ln, os.SEEK_CUR)
                pos += 4 + ln
                n += 1
    except OSError:
        return 0
    return n


def committed(bus_dir: str) -> int:
    try:
        with open(os.path.join(bus_dir, "doOrder.offset"), "rb") as f:
            m = _OFF_RE.match(f.read())
        return int(m.group(1)) if m else 0
    except OSError:
        return 0


def await_committed(bus_dir: str, target: int, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if committed(bus_dir) >= target:
            return True
        time.sleep(0.2)
    return False


# -- parent ------------------------------------------------------------------


class Fleet:
    """Process bookkeeping: current worker per slot plus the full
    lifetime ledger (every spawn's armed class + observed exit code —
    the injected-deaths-only check reads this)."""

    def __init__(
        self, work: str, bus_dirs: list, snap_dirs: list, resp_ports: list,
    ):
        self.work = work
        self.bus_dirs = bus_dirs
        self.snap_dirs = snap_dirs
        self.resp_ports = resp_ports
        self.current: dict[str, Worker] = {}
        self.lifetimes: list[dict] = []
        self._n = 0

    def spawn(
        self, role: str, p: int, plan_path: str | None = None,
        armed: str | None = None, ready_timeout_s: float = 300.0,
    ) -> Worker:
        name = ("gw" if role == "gateway" else "c") + str(p)
        self._n += 1
        result = os.path.join(self.work, f"{name}_L{self._n}.json")
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--worker", role,
            "--bus-dir", self.bus_dirs[p],
            "--partition", str(p),
            "--result", result,
            "--resp-port", str(self.resp_ports[p]),
        ]
        if role == "consumer":
            cmd += ["--snap-dir", self.snap_dirs[p]]
        if plan_path:
            cmd += ["--plan", plan_path]
        w = Worker(name, cmd)
        w.await_ready(timeout_s=ready_timeout_s)
        self.current[name] = w
        self.lifetimes.append({
            "name": name, "role": role, "partition": p, "lifetime": self._n,
            "armed": armed, "result": result, "exit_code": None,
        })
        w.ledger = self.lifetimes[-1]
        return w

    def note_exit(self, w: Worker, rc: int) -> None:
        w.ledger["exit_code"] = rc

    def stop(self, name: str) -> int:
        w = self.current.pop(name, None)
        if w is None:
            return 0
        rc = w.stop(timeout_s=90.0)
        self.note_exit(w, rc)
        return rc

    def result_of(self, name: str) -> dict:
        for lt in reversed(self.lifetimes):
            if lt["name"] == name:
                try:
                    with open(lt["result"]) as f:
                        return json.load(f)
                except (OSError, ValueError):
                    return {}
        return {}


def run_oracle(work: str, bus_dir: str, p: int) -> tuple[int, dict, str]:
    """Uninterrupted replay of partition p's durable order log through
    scripts/chaos.py --worker (same consumer code path, same engine
    geometry, fresh snapshot dir) — the bit-exactness baseline."""
    obus = os.path.join(work, f"oracle{p}", "bus")
    osnap = os.path.join(work, f"oracle{p}", "snaps")
    os.makedirs(obus, exist_ok=True)
    os.makedirs(osnap, exist_ok=True)
    # Copy ONLY the log: no offset sidecar, so the oracle consumes from 0.
    shutil.copyfile(
        os.path.join(bus_dir, "doOrder.log"),
        os.path.join(obus, "doOrder.log"),
    )
    out = os.path.join(work, f"oracle{p}_result.json")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "chaos.py"), "--worker",
            "--bus-dir", obus, "--snap-dir", osnap, "--out", out,
        ],
        timeout=1200,
    )
    try:
        with open(out) as f:
            return proc.returncode, json.load(f), obus
    except (OSError, ValueError):
        return proc.returncode, {}, obus


def run_parent(args) -> int:
    import tempfile

    from gome_tpu.fleet import FailoverController, HealthGate, PartitionMap

    work = args.workdir or tempfile.mkdtemp(prefix="gome-fleet-chaos-")
    os.makedirs(work, exist_ok=True)
    n_steps = max(32, min(480, args.seconds * 8))
    print(f"fleet-chaos: recording {n_steps} sim steps (seed {args.seed})...")
    frames = record_sim_frames(args.seed, n_steps)
    parts = requests_from_frames(frames)
    base_counts = [len(p) for p in parts]
    print(f"fleet-chaos: {len(frames)} frames -> base round "
          f"{base_counts} orders/partition in {work}")

    bus_dirs, snap_dirs = [], []
    for i in range(N_PARTITIONS):
        bus_dirs.append(os.path.join(work, f"p{i}", "bus"))
        snap_dirs.append(os.path.join(work, f"p{i}", "snaps"))
        os.makedirs(bus_dirs[i], exist_ok=True)
        os.makedirs(snap_dirs[i], exist_ok=True)

    # One marker store per partition (never a kill target: the store's own
    # failure mode is PR 9's supervised-client drill). Per-partition keeps
    # the book digest honest — pre-pool iteration is store-wide.
    resp = [start_respserver(work) for _ in range(N_PARTITIONS)]
    resp_ports = [r.resp_port for r in resp]
    print(f"fleet-chaos: marker stores on ports {resp_ports}")

    fleet = Fleet(work, bus_dirs, snap_dirs, resp_ports)
    ctl = DriveCtl()

    # Router tier state the failover drill runs over: consumer members
    # own partitions; health is ground truth from the parent's process
    # monitoring (mark_down on an observed death — the poll-debounce path
    # is unit-tested, a watched SIGKILL needs no debounce).
    pmap = PartitionMap(
        N_PARTITIONS, {i: f"m{i}" for i in range(N_PARTITIONS)}
    )
    gate = HealthGate()
    fc = FailoverController(pmap, gate)

    cycles: list[dict] = []
    recoveries: list[float] = []
    all_ready = False
    drained_final = [False] * N_PARTITIONS
    t_run0 = time.monotonic()

    def now() -> float:
        return round(time.monotonic() - t_run0, 3)

    try:
        for i in range(N_PARTITIONS):
            fleet.spawn("consumer", i)
            gw = fleet.spawn("gateway", i)
            ctl.targets[i] = f"127.0.0.1:{gw.ports['grpc']}"
        all_ready = True
        for i in range(N_PARTITIONS):
            gate.record(f"m{i}", True)
            gate.record(f"gw{i}", True)
        print("fleet-chaos: 2x2 fleet up "
              f"(targets {sorted(ctl.targets.items())})")

        # Warm round: trigger the consumers' first-dispatch compiles so
        # cycle recovery times measure recovery, not cold-start skew.
        done = threading.Event()
        done.set()
        for t in drive_burst(ctl, parts, "warm", done, min_rounds=1):
            t.join(timeout=300)
        for i in range(N_PARTITIONS):
            await_committed(bus_dirs[i], log_end(bus_dirs[i]), 240.0)
        print(f"fleet-chaos: warm round done at t={now()}s "
              f"(accepted {[ctl.stat(p, 'accepted') for p in range(2)]})")

        for c in range(1, args.kills + 1):
            klass, p = class_for_cycle(c)
            plan = plan_for_cycle(c, args.seed, klass)
            plan_path = os.path.join(work, f"plan_{c}.json")
            with open(plan_path, "w") as f:
                f.write(plan.to_json())
            victim_name = ("c" if klass == "consumer-kill" else "gw") + str(p)
            cyc: dict = {
                "cycle": c, "class": klass, "partition": p,
                "victim": victim_name, "plan": plan.to_dict(),
                "t_armed": now(),
            }
            print(f"fleet-chaos: cycle {c} [{klass}] partition {p} "
                  f"-> arming {victim_name}")

            # Re-arm by restart: graceful stop, spawn with the plan. No
            # drive is in flight between bursts, so the stop is clean.
            fleet.stop(victim_name)
            victim = fleet.spawn(
                "consumer" if klass == "consumer-kill" else "gateway",
                p, plan_path=plan_path, armed=klass,
            )
            if klass != "consumer-kill":
                ctl.targets[p] = f"127.0.0.1:{victim.ports['grpc']}"

            done = threading.Event()
            threads = drive_burst(ctl, parts, f"c{c}", done, min_rounds=1)
            try:
                if klass == "bus-disconnect":
                    # No death: the armed gateway soft-fails three emits
                    # (CODE_RETRYABLE); wait until the drivers' retry
                    # tallies show all three absorbed.
                    base_disc = ctl.stat(p, "disconnect_retries")
                    deadline = time.monotonic() + 180.0
                    while time.monotonic() < deadline:
                        if ctl.stat(p, "disconnect_retries") - base_disc >= 3:
                            break
                        time.sleep(0.25)
                    cyc["disconnect_retries"] = (
                        ctl.stat(p, "disconnect_retries") - base_disc
                    )
                    cyc["recovery_s"] = None
                    print(f"fleet-chaos: cycle {c} absorbed "
                          f"{cyc['disconnect_retries']} disconnects")
                else:
                    rc = victim.proc.wait(timeout=360)
                    t_death = time.monotonic()
                    fleet.note_exit(victim, rc)
                    fleet.current.pop(victim_name, None)
                    cyc["victim_exit"] = rc
                    cyc["t_death"] = now()
                    print(f"fleet-chaos: cycle {c} {victim_name} died "
                          f"rc={rc} at t={cyc['t_death']}s")
                    if klass == "consumer-kill":
                        dead = pmap.owner(p)
                        gate.mark_down(dead)
                        standby = f"m{p}s{c}"
                        # Park p's driver (health-gated shed) and wait for
                        # the in-flight chunk to land: the standby's restore
                        # rebuilds the shared mark store from the durable
                        # log, which must not race live gateway marking.
                        ctl.pause[p].set()
                        ctl.idle[p].wait(timeout=120.0)
                        target = log_end(bus_dirs[p])

                        def recover(dead_member, partitions):
                            fleet.spawn("consumer", p)
                            if not await_committed(
                                bus_dirs[p], target,
                                args.recovery_timeout,
                            ):
                                raise RuntimeError(
                                    f"standby for {dead_member} never "
                                    f"caught up to {target}"
                                )

                        # Reassignment ONLY after durable recovery: the
                        # claim->recover->commit protocol under test.
                        try:
                            epoch = fc.failover(dead, standby, recover)
                        finally:
                            ctl.pause[p].clear()
                        rec_s = time.monotonic() - t_death
                        gate.record(standby, True)
                        cyc["failover"] = {
                            "dead": dead, "standby": standby,
                            "epoch": epoch,
                        }
                    else:  # gateway-kill
                        gate.mark_down(f"gw{p}")
                        gw = fleet.spawn("gateway", p)
                        ctl.targets[p] = f"127.0.0.1:{gw.ports['grpc']}"
                        rec_s = time.monotonic() - t_death
                        gate.record(f"gw{p}", True)
                    cyc["recovery_s"] = round(rec_s, 3)
                    recoveries.append(rec_s)
                    cyc["degraded"] = window_rate(
                        ctl, t_death, t_death + rec_s
                    )
                    print(f"fleet-chaos: cycle {c} recovered in "
                          f"{rec_s:.1f}s (degraded window "
                          f"{cyc['degraded']['orders_per_s']} orders/s)")
            finally:
                done.set()
            for t in threads:
                t.join(timeout=300)
            cyc["t_done"] = now()
            cycles.append(cyc)

        # -- final drain: gateways are idle, ends are stable ------------
        for i in range(N_PARTITIONS):
            backlog = log_end(bus_dirs[i]) - committed(bus_dirs[i])
            drained_final[i] = await_committed(
                bus_dirs[i], log_end(bus_dirs[i]),
                120.0 + backlog / 4096.0,
            )
        print(f"fleet-chaos: final drain={drained_final} at t={now()}s")
    finally:
        for name in [f"gw{i}" for i in range(N_PARTITIONS)] + [
            f"c{i}" for i in range(N_PARTITIONS)
        ]:
            fleet.stop(name)
        # Any stragglers (distinct lifetimes) die hard.
        for w in list(fleet.current.values()):
            w.kill()
        # Marker stores outlive the consumers: the final graceful stop
        # reads the pool (book digest) through them.
        for rp in resp:
            rp.kill()

    # -- oracle replays + durable audits (everyone is dead now) ---------
    partitions = []
    for i in range(N_PARTITIONS):
        final = fleet.result_of(f"c{i}")
        orc, oracle, obus = run_oracle(work, bus_dirs[i], i)
        fleet_lines, fleet_seqs = read_match_stream(bus_dirs[i])
        oracle_lines, _ = read_match_stream(obus)
        partitions.append({
            "partition": i,
            "events": len(fleet_lines),
            "stamped": len(fleet_seqs),
            "seq_audit": audit_seqs(fleet_seqs),
            "book_digest": final.get("book_digest"),
            "oracle_digest": oracle.get("book_digest"),
            "digest_match": (
                bool(final.get("book_digest"))
                and final.get("book_digest") == oracle.get("book_digest")
            ),
            "match_stream_identical": (
                len(fleet_lines) > 0 and fleet_lines == oracle_lines
            ),
            "match_seq": final.get("match_seq"),
            "oracle_match_seq": oracle.get("match_seq"),
            "feed": final.get("feed"),
            "oracle_exit": orc,
        })
        print(f"fleet-chaos: partition {i} digest "
              f"{'MATCH' if partitions[-1]['digest_match'] else 'MISMATCH'} "
              f"({len(fleet_lines)} events)")

    # -- verdict --------------------------------------------------------
    death_cycles = [c for c in cycles if c["class"] != "bus-disconnect"]
    disc_cycles = [c for c in cycles if c["class"] == "bus-disconnect"]
    stats = {str(p): dict(ctl.stats[p]) for p in range(N_PARTITIONS)}
    checks = {
        "all_members_ready": all_ready,
        "injected_deaths_only": bool(fleet.lifetimes) and all(
            lt["exit_code"] == (
                EXIT_CODE
                if lt["armed"] in ("consumer-kill", "gateway-kill")
                else 0
            )
            for lt in fleet.lifetimes
        ),
        "covered_fault_classes": (
            {c["class"] for c in cycles} >= set(CLASSES)
        ),
        "disconnect_absorbed": bool(disc_cycles) and all(
            c.get("disconnect_retries", 0) >= 3 for c in disc_cycles
        ),
        "no_lost_entries": all(
            s["aborted"] == 0 for s in stats.values()
        ),
        "all_partitions_drained": all(drained_final),
        "book_digest_match": all(p["digest_match"] for p in partitions),
        "match_stream_identical": all(
            p["match_stream_identical"] for p in partitions
        ),
        "exactly_once_fleet": all(
            p["seq_audit"]["dupes"] == 0 and p["seq_audit"]["gaps"] == 0
            and (p["feed"] or {}).get("dupes") == 0
            and (p["feed"] or {}).get("gaps") == 0
            for p in partitions
        ),
        "failover_after_recovery": all(
            (c.get("failover") or {}).get("epoch") is not None
            for c in cycles if c["class"] == "consumer-kill"
        ) and any(c["class"] == "consumer-kill" for c in cycles),
        "recovery_measured": len(recoveries) == len(death_cycles),
        "recovery_bounded": (
            bool(recoveries)
            and pctl(recoveries, 99) <= args.recovery_bound
        ),
        "throughput_floor_degraded": bool(death_cycles) and all(
            c["degraded"]["orders_per_s"] >= args.floor
            for c in death_cycles
        ),
        "oracle_clean_exit": all(
            p["oracle_exit"] == 0 for p in partitions
        ),
    }
    verdict = {
        "schema": SCHEMA,
        "config": {
            "seed": args.seed,
            "seconds": args.seconds,
            "kills": args.kills,
            "n_steps": n_steps,
            "base_orders_per_partition": base_counts,
            "partitions": N_PARTITIONS,
            "drive_chunk": DRIVE_CHUNK,
            "pace_s": PACE_S,
            "hit_k": HIT_K,
            "floor_orders_per_s": args.floor,
            "recovery_bound_s": args.recovery_bound,
            "admission_max_depth": args.max_depth,
            "every_n_batches": EVERY_N,
        },
        "cycles": cycles,
        "recovery": {
            "samples_s": [round(r, 3) for r in recoveries],
            "p50_s": pctl(recoveries, 50),
            "p99_s": pctl(recoveries, 99),
        },
        "throughput": {
            "degraded_windows": {
                str(c["cycle"]): c["degraded"] for c in death_cycles
            },
            "floor_orders_per_s": args.floor,
            "fleet_r01_orders_per_s": 410.0,
        },
        "drivers": stats,
        "router": {
            "map": pmap.snapshot(),
            "failovers": fc.history(),
            "health": gate.snapshot(),
        },
        "partitions": partitions,
        "lifetimes": [
            {k: lt[k] for k in
             ("name", "role", "partition", "lifetime", "armed", "exit_code")}
            for lt in fleet.lifetimes
        ],
        "checks": checks,
        "pass": all(checks.values()),
    }
    write_json(args.out, verdict)
    status = "PASS" if verdict["pass"] else "FAIL"
    print(f"fleet-chaos: {status} -> {args.out}")
    for name, ok in checks.items():
        print(f"  [{'ok' if ok else 'BREACH'}] {name}")
    return 0 if verdict["pass"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seconds", type=int, default=30,
                    help="soak scale knob: sim steps = seconds*8 (clamped)")
    ap.add_argument("--kills", type=int, default=3,
                    help="kill/restart cycles (fault class rotates)")
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--out", default="FLEET_CHAOS_r01.json",
                    help="verdict JSON path (parent mode)")
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: fresh tempdir)")
    ap.add_argument("--floor", type=float, default=100.0,
                    help="degraded-window aggregate floor, orders/sec "
                         "(~0.25x FLEET_r01's 410)")
    ap.add_argument("--recovery-bound", type=float, default=150.0,
                    help="p99 recovery ceiling, seconds (CPU compile "
                         "inclusive)")
    ap.add_argument("--recovery-timeout", type=float, default=300.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--max-depth", type=int, default=16384,
                    help="gateway admission depth ceiling")
    # worker mode (internal)
    ap.add_argument("--worker", choices=("gateway", "consumer"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--bus-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--snap-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--plan", default="", help=argparse.SUPPRESS)
    ap.add_argument("--partition", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--result", default="", help=argparse.SUPPRESS)
    ap.add_argument("--resp-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.worker == "gateway":
        return run_gateway_worker(args)
    if args.worker == "consumer":
        return run_consumer_worker(args)
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())

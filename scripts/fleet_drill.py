#!/usr/bin/env python
"""Fleet drill: a real 2-gateway x 2-consumer fleet with a machine-checked
aggregate-observability verdict.

The question this script answers: when the engine is deployed as a
PARTITIONED fleet (disjoint symbol sets, one gateway + one consumer per
partition, split processes sharing a file bus + RESP marker store — the
reference's own three-process shape, scaled out sideways), can one
aggregator process (obs.fleet.FLEET) see the whole thing: merged
/metrics that are lossless over the members, a health/degradation
rollup that stays green for the entire run, a fleet-wide exactly-once
seq audit, and order journeys STITCHED across the gateway/consumer
process boundary into one timeline?

Topology (parent drives everything; 5 children):

    parent                              children (this script, --worker)
    ------                              -----------------------------
    record sim GCO frames               respserver (RESP marker store)
    decode -> per-order requests        gw0, gw1: OrderGateway + gRPC
    route via fleet.partition_of            + ops server (file bus p{i})
    drive both partitions over gRPC     c0, c1: full EngineService
    FLEET polls all 4 ops servers           (consumer + matchfeed + ops)
    drain via /durability; stitch
    journeys; audit seqs; verdict

Partitioning rides the fleet router tier (gome_tpu.fleet, round 12):
`fleet.partition_of` is the consistent fnv1a symbol hash every layer of
the tree shares (parallel/router.py in-process, the fleet PartitionMap
across members), so the drill's routing and the failover drill's routing
are the SAME function — N independent single-partition deployments plus
the aggregator ARE a fleet. The verdict's imbalance row records how
evenly that hash spread this run's symbols (a skewed draw is a property
of the symbol set, not a routing bug — the explicit PartitionMap is the
rebalance lever).

The verdict JSON (committed as FLEET_r01.json, pinned by
tests/test_fleet.py) records the aggregate throughput table (per-proc
orders/sec + getrusage, fleet total, stitched end-to-end latency
percentiles), the health rollup, the merge-losslessness proof, the
fleet-wide seq audit, and a pass/fail per check. CI runs this with
``--seconds 30`` and fails the build on any breach.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Must be set before anything imports jax (workers inherit it too).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SCHEMA = "gome-fleet-verdict-v1"

N_PARTITIONS = 2

# Worker geometry: small enough to compile in seconds on CPU, matched to
# the sim flow below (n_slots >= n_lanes, max_t >= t_bins).
N_LANES = 16
T_BINS = 8


def partition_of(symbol: str) -> int:
    """Stable symbol -> partition routing via the fleet router tier
    (gome_tpu.fleet.partition_of, fnv1a): every process in the fleet can
    recompute it from the symbol alone, and it is the SAME mapping the
    failover drill's PartitionMap assigns members over. Lazy import —
    the module body must not import gome_tpu before JAX_PLATFORMS is
    pinned."""
    from gome_tpu.fleet import partition_of as _partition_of

    return _partition_of(symbol, N_PARTITIONS)


def rusage_self() -> dict:
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    return {
        "utime_s": round(ru.ru_utime, 4),
        "stime_s": round(ru.ru_stime, 4),
        "maxrss_kb": ru.ru_maxrss,
    }


def write_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# -- workers -----------------------------------------------------------------
#
# Protocol (both roles): print one "READY ops=<port> grpc=<port>" line on
# stdout once serving, then block reading stdin; any line (or EOF) is the
# stop signal. On stop: write the result JSON to --result, tear down,
# exit 0.


def _await_stop() -> None:
    try:
        sys.stdin.readline()
    except Exception:
        pass


def run_gateway_worker(args) -> int:
    """One partition's front door: OrderGateway over the partition's file
    bus, pre-pool marks in the shared RESP store, gRPC listener, and its
    OWN ops server (/metrics + /trace + /timeline) for the aggregator to
    scrape. No engine, no consumer — journeys opened here complete in
    the consumer process; stitching joins them."""
    from gome_tpu.bus import make_bus
    from gome_tpu.bus.base import export_queue_metrics
    from gome_tpu.config import BusConfig, Config, GrpcConfig
    from gome_tpu.engine.prepool import RespPrePool, make_marker
    from gome_tpu.obs.timeline import TIMELINE
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.service.gateway import OrderGateway, serve_gateway
    from gome_tpu.service.ops import OpsServer
    from gome_tpu.utils.trace import TRACER, FlightRecorder

    bus = make_bus(
        BusConfig(backend="file", dir=args.bus_dir, match_wire="frame")
    )
    export_queue_metrics(bus.order_queue)
    export_queue_metrics(bus.match_queue)
    # Gateway-side journeys never complete locally (the consumer closes
    # them): a deep open ring keeps the tail of the run joinable.
    TRACER.install(FlightRecorder(keep_n=512, max_open=8192))
    TIMELINE.install(interval_s=0.25, keep_n=256)
    pool = RespPrePool(RespClient(port=args.resp_port))
    gateway = OrderGateway(bus, accuracy=0, mark=make_marker(pool))
    server = serve_gateway(
        gateway, Config(grpc=GrpcConfig(host="127.0.0.1", port=0))
    )
    ops = OpsServer(service=None, host="127.0.0.1", port=0)
    ops.start()
    TIMELINE.start()
    print(f"READY ops={ops.port} grpc={server.bound_port}", flush=True)
    _await_stop()
    result = {
        "role": "gateway",
        "partition": args.partition,
        "published": {
            "doOrder": bus.order_queue.end_offset(),
        },
        "rusage": rusage_self(),
    }
    write_json(args.result, result)
    server.stop(grace=1).wait()
    TIMELINE.stop()
    ops.stop()
    return 0


def run_consumer_worker(args) -> int:
    """One partition's engine half: a full EngineService (consumer +
    matchfeed + ops server) over the partition's file bus, marker store
    attached so admission consumes the gateway's pre-pool marks."""
    from gome_tpu.config import (
        BusConfig, Config, EngineConfig, GrpcConfig, OpsConfig, StoreConfig,
    )
    from gome_tpu.service.app import EngineService

    # Per-event match logging is operator chrome; at drill rates it
    # floods the parent's console.
    import logging

    logging.getLogger("gome_tpu.matchfeed").setLevel(logging.WARNING)
    svc = EngineService(Config(
        grpc=GrpcConfig(host="127.0.0.1", port=0),
        bus=BusConfig(backend="file", dir=args.bus_dir, match_wire="frame"),
        engine=EngineConfig(
            accuracy=0, cap=64, max_fills=8, n_slots=N_LANES, max_t=T_BINS,
            dtype="int64", kernel="scan",
        ),
        store=StoreConfig(enabled=True, host="127.0.0.1", port=args.resp_port),
        ops=OpsConfig(
            enabled=True, host="127.0.0.1", port=0,
            trace=True, trace_keep=4096,
            timeline=True, timeline_interval_s=0.25,
            cost=False, profile=False, hostprof=False,
        ),
    ))
    svc.start()
    print(f"READY ops={svc.ops.port} grpc=0", flush=True)
    _await_stop()
    oq, mq = svc.bus.order_queue, svc.bus.match_queue
    result = {
        "role": "consumer",
        "partition": args.partition,
        "orders_consumed": oq.committed(),
        "feed": svc.feed.seq_state(),
        "oq": {"end": oq.end_offset(), "committed": oq.committed()},
        "mq": {"end": mq.end_offset(), "committed": mq.committed()},
        "rusage": rusage_self(),
    }
    write_json(args.result, result)
    svc.stop()
    return 0


# -- parent ------------------------------------------------------------------


def record_sim_frames(seed: int, n_steps: int) -> list[bytes]:
    from gome_tpu.sim.env import EnvConfig
    from gome_tpu.sim.flow import FlowConfig
    from gome_tpu.sim.replay import record_frames

    cfg = EnvConfig(flow=FlowConfig(
        n_lanes=N_LANES, t_bins=T_BINS, dt=0.07,
        submit_rate=3.0, cancel_rate=1.5, market_rate=1.0,
    ))
    return record_frames(cfg, seed, n_steps)


def requests_from_frames(frames: list[bytes]) -> list[list]:
    """Decode recorded GCO frames into per-partition gRPC request
    streams: [(global_idx, is_cancel, OrderRequest), ...] per partition,
    global arrival order preserved inside each partition (the
    ADD-before-DEL sequencing contract only spans one symbol, and a
    symbol maps to exactly one partition). global_idx is the order's
    rank in the SIM's arrival stream — the open-loop scheduler's clock
    ticks on it, so both partitions share one arrival process."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.bus.colwire import decode_order_frame

    parts: list[list] = [[] for _ in range(N_PARTITIONS)]
    gi = 0
    for fr in frames:
        cols = decode_order_frame(fr)
        symbols, uuids = cols["symbols"], cols["uuids"]
        for i in range(cols["n"]):
            action = int(cols["action"][i])
            if action == 0:  # NOP padding never reaches the wire
                continue
            symbol = symbols[int(cols["symbol_idx"][i])]
            req = pb.OrderRequest(
                uuid=uuids[int(cols["uuid_idx"][i])],
                oid=cols["oids"][i].decode(),
                symbol=symbol,
                transaction=int(cols["side"][i]),
                price=float(int(cols["price"][i])),
                volume=float(int(cols["volume"][i])),
                kind=int(cols["kind"][i]),
            )
            parts[partition_of(symbol)].append((gi, action == 2, req))
            gi += 1
    return parts


#: Orders per DoOrderBatch RPC when driving a partition. Matches the
#: columnar admit drill's unit (gateway STREAM_CHUNK is 4096; 1024
#: amortizes the round trip without giant messages).
DRIVE_BATCH_N = 1024


def drive_partition(target: str, reqs: list, out: dict,
                    sched=None, rank=None,
                    batch_n: int = DRIVE_BATCH_N) -> None:
    """Chunked gRPC drive of one partition's gateway through the
    columnar batch front door (round 11): DoOrderBatch with per-chunk
    cancel masks, arrival order preserved (adds and cancels ride the
    SAME request stream, so the ADD-before-DEL sequencing contract
    holds exactly as it did under per-order DoOrder). Tallies per-order
    response codes (accepted entries count as code 0, rejects by their
    per-order code); any transport error is recorded, not raised.

    With ``sched`` (an ``OpenLoopSchedule``) the drive is RATE
    CONTROLLED (ISSUE 17): each chunk waits for the intended arrival
    time of its last order (``rank`` maps global order index ->
    schedule tick), and is sent immediately when behind — the backlog
    is the system's to answer for, never forgiven. Without it, the
    legacy closed-loop fire-hose."""
    import grpc

    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.api.service import OrderStub

    codes: dict[int, int] = {}
    t0 = time.perf_counter()
    try:
        with grpc.insecure_channel(target) as channel:
            stub = OrderStub(channel)
            for i in range(0, len(reqs), batch_n):
                chunk = reqs[i : i + batch_n]
                if sched is not None:
                    due = sched.intended(
                        max(rank[g] for g, _, _ in chunk)
                        if rank is not None
                        else max(g for g, _, _ in chunk)
                    )
                    now = time.perf_counter()
                    if now < due:
                        time.sleep(due - now)
                breq = pb.OrderBatchRequest(
                    orders=[r for _, _, r in chunk],
                    cancel=[c for _, c, _ in chunk],
                )
                resp = stub.DoOrderBatch(breq, timeout=30)
                codes[0] = codes.get(0, 0) + resp.accepted
                for r in resp.rejects:
                    codes[r.code] = codes.get(r.code, 0) + 1
                # A batch-level abort (code != 0) leaves the tail of the
                # chunk unaccounted: record it under the batch code so
                # sent == sum(codes) still holds for the audit.
                seen = resp.accepted + len(resp.rejects)
                if resp.code != 0 and seen < len(chunk):
                    codes[resp.code] = (
                        codes.get(resp.code, 0) + len(chunk) - seen
                    )
    except grpc.RpcError as exc:  # pragma: no cover - transport breach
        out["transport_error"] = str(exc)
    out["codes"] = {str(k): v for k, v in sorted(codes.items())}
    out["sent"] = len(reqs)
    out["wall_s"] = time.perf_counter() - t0


def fetch_json(url: str, timeout_s: float = 2.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def await_drained(ops_url: str, expect_orders: int, timeout_s: float) -> bool:
    """Poll one consumer's /durability until its order queue has consumed
    everything the gateway published and the match feed has caught up
    with the match queue."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            doc = fetch_json(ops_url + "/durability")
            queues = doc.get("queues") or {}
            oq = queues.get("order_queue") or {}
            mq = queues.get("match_queue") or {}
            if (
                oq.get("committed", -1) >= expect_orders
                and mq.get("committed", -1) >= mq.get("end", 0)
            ):
                return True
        except Exception:
            pass
        time.sleep(0.1)
    return False


def read_match_seqs(bus_dir: str) -> tuple[int, list[int]]:
    """The durable queue-level record for one partition: event count and
    the raw seq sequence for the exactly-once audit."""
    from gome_tpu.bus.colwire import decode_event_frame
    from gome_tpu.bus.filelog import FileQueue

    q = FileQueue("matchOrder", os.path.join(bus_dir, "matchOrder"))
    n_events = 0
    seqs: list[int] = []
    for m in q.read_from(0, q.end_offset()):
        batch = decode_event_frame(m.body)
        for r in batch.to_results():
            n_events += 1
            if r.seq is not None:
                seqs.append(r.seq)
    q.close()
    return n_events, seqs


def audit_seqs(seqs: list[int]) -> dict:
    from gome_tpu.service.matchfeed import SeqTracker

    tracker = SeqTracker(first_seq=0)
    for s in seqs:
        tracker.observe(s)
    return tracker.state()


def pctl(xs: list[float], p: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))]


class Worker:
    """One child process with the READY/stdin-stop protocol."""

    def __init__(self, name: str, cmd: list[str]):
        self.name = name
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True
        )
        self.ports: dict[str, int] = {}

    def await_ready(self, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        line = ""
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"{self.name} exited before READY "
                    f"(rc={self.proc.poll()})"
                )
            if line.startswith("READY "):
                for tok in line.split()[1:]:
                    key, _, val = tok.partition("=")
                    self.ports[key] = int(val)
                return
        raise RuntimeError(f"{self.name} never became READY: {line!r}")

    def stop(self, timeout_s: float = 60.0) -> int:
        try:
            self.proc.stdin.write("STOP\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass
        try:
            return self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            return self.proc.wait(timeout=10)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def start_respserver(work: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gome_tpu.persist.respserver", "--port", "0"],
        stdout=subprocess.PIPE, text=True, cwd=work, env=env,
    )
    line = proc.stdout.readline()
    if not line.startswith("READY "):
        proc.kill()
        raise RuntimeError(f"respserver handshake failed: {line!r}")
    proc.resp_port = int(line.split()[1])
    return proc


def run_parent(args) -> int:
    import tempfile

    from gome_tpu.obs.fleet import FLEET, stitch_journeys
    from gome_tpu.utils.metrics import (
        family_total, merge_expositions, parse_exposition, render_exposition,
    )

    work = args.workdir or tempfile.mkdtemp(prefix="gome-fleet-")
    os.makedirs(work, exist_ok=True)
    n_steps = max(32, min(480, args.seconds * 8))
    print(f"fleet: recording {n_steps} sim steps (seed {args.seed})...")
    frames = record_sim_frames(args.seed, n_steps)
    parts = requests_from_frames(frames)
    n_orders = sum(len(p) for p in parts)
    sym_counts = [
        len({r.symbol for _, _, r in p}) for p in parts
    ]
    print(
        f"fleet: {len(frames)} frames / {n_orders} orders -> "
        f"partitions {[len(p) for p in parts]} "
        f"(symbols {sym_counts}) in {work}"
    )

    resp = None
    workers: dict[str, Worker] = {}
    try:
        resp = start_respserver(work)
        bus_dirs = []
        for i in range(N_PARTITIONS):
            bus_dir = os.path.join(work, f"p{i}", "bus")
            os.makedirs(bus_dir, exist_ok=True)
            bus_dirs.append(bus_dir)
            for role in ("consumer", "gateway"):
                name = ("c" if role == "consumer" else "gw") + str(i)
                workers[name] = Worker(name, [
                    sys.executable, os.path.abspath(__file__),
                    "--worker", role,
                    "--bus-dir", bus_dir,
                    "--resp-port", str(resp.resp_port),
                    "--partition", str(i),
                    "--result", os.path.join(work, f"{name}_result.json"),
                ])
        for name, w in workers.items():
            w.await_ready()
            print(f"fleet: {name} ready (ops={w.ports['ops']}, "
                  f"grpc={w.ports['grpc']})")

        members = {
            name: f"http://127.0.0.1:{w.ports['ops']}"
            for name, w in workers.items()
        }
        FLEET.install(members, interval_s=0.25, timeout_s=2.0)
        FLEET.start()

        def drive_all(slices: list, out: dict, sched=None,
                      rank=None, batch_n: int = DRIVE_BATCH_N) -> None:
            threads = [
                threading.Thread(
                    target=drive_partition,
                    args=(
                        f"127.0.0.1:{workers[f'gw{i}'].ports['grpc']}",
                        slices[i], out[f"gw{i}"], sched, rank, batch_n,
                    ),
                )
                for i in range(N_PARTITIONS)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        # -- warm-up slice, drained before the measured window ----------
        # The first device dispatches compile (seconds on CPU); an
        # open-loop drive during compile measures XLA warm-up backlog,
        # not the fleet. The warm-up slice triggers those compiles and
        # the table below covers only the steady remainder.
        warm_n = [min(48, len(p) // 3) for p in parts]
        warm: dict[str, dict] = {f"gw{i}": {} for i in range(N_PARTITIONS)}
        drive_all([parts[i][:warm_n[i]] for i in range(N_PARTITIONS)], warm)
        warm_drained = [
            await_drained(members[f"c{i}"], warm_n[i], timeout_s=120.0)
            for i in range(N_PARTITIONS)
        ]
        print(f"fleet: warm-up {warm_n} drained={warm_drained}")

        # -- measured drive of both partitions concurrently over gRPC ---
        # Rate controlled (ISSUE 17): a shared OpenLoopSchedule at
        # --rate ticks on the SIM's global arrival order (warm-up slice
        # re-ranked out), so both gateways see one coherent open-loop
        # arrival process. The old fire-hose drive made the verdict's
        # orders/s an artifact of feed size, not a chosen offered rate.
        measured_slices = [parts[i][warm_n[i]:] for i in range(N_PARTITIONS)]
        rank = {
            gi: k for k, gi in enumerate(sorted(
                gi for sl in measured_slices for gi, _, _ in sl
            ))
        }
        sched = None
        if args.rate > 0:
            from gome_tpu.obs.capacity import OpenLoopSchedule

            sched = OpenLoopSchedule(args.rate, t0=time.perf_counter())
        drive: dict[str, dict] = {f"gw{i}": {} for i in range(N_PARTITIONS)}
        t0 = time.perf_counter()
        drive_all(measured_slices, drive, sched=sched, rank=rank,
                  batch_n=args.drive_batch)
        drive_wall = time.perf_counter() - t0
        n_measured = n_orders - sum(warm_n)
        print(f"fleet: drive done in {drive_wall:.2f}s "
              f"({n_measured / drive_wall:.0f} orders/s aggregate, "
              f"offered rate "
              f"{args.rate if args.rate > 0 else 'closed-loop'})")

        # -- drain, then hold a steady observation window ---------------
        drained = [
            await_drained(
                members[f"c{i}"], len(parts[i]), timeout_s=60.0
            )
            for i in range(N_PARTITIONS)
        ]
        print(f"fleet: drained={drained}")
        window_s = max(2.0, min(10.0, args.seconds * 0.1))
        time.sleep(window_s)

        # -- stitch journeys BEFORE stopping members --------------------
        exports = FLEET.journeys()
        stitch = stitch_journeys(exports)
        per_part_stitched = []
        for i in range(N_PARTITIONS):
            pair = {f"gw{i}", f"c{i}"}
            per_part_stitched.append(sum(
                1 for j in stitch["journeys"] if pair <= set(j["procs"])
            ))
        print(f"fleet: stitched {stitch['joined']}/{stitch['traces']} "
              f"traces (per partition {per_part_stitched}, "
              f"offsets {stitch['offsets']})")

        # -- merged metrics + losslessness proof ------------------------
        member_exps = {}
        for name, url in members.items():
            member_exps[name] = FLEET._fetch(url + "/metrics", 2.0)
        merged = merge_expositions(member_exps)
        merged_text = render_exposition(merged)
        reparsed = parse_exposition(merged_text)
        merge_roundtrip_ok = render_exposition(reparsed) == merged_text
        consumed_fam = merged.get("gome_orders_consumed_total")
        member_consumed = 0.0
        for text in member_exps.values():
            fam = parse_exposition(text).get("gome_orders_consumed_total")
            if fam is not None:
                member_consumed += family_total(fam)
        merged_consumed = family_total(consumed_fam) if consumed_fam else -1.0
        accepted = sum(
            d.get("codes", {}).get("0", 0)
            for phase in (warm, drive) for d in phase.values()
        )

        payload = FLEET.payload()
        rollup = FLEET.rollup()
        FLEET.stop()
    finally:
        results: dict[str, dict] = {}
        for name, w in workers.items():
            rc = w.stop()
            path = os.path.join(work, f"{name}_result.json")
            try:
                with open(path) as f:
                    results[name] = json.load(f)
            except (OSError, ValueError):
                results[name] = {}
            results[name]["exit_code"] = rc
        if resp is not None:
            resp.kill()
            resp.wait(timeout=10)
        from gome_tpu.obs.fleet import FLEET as _F

        _F.disable()

    # -- queue-level fleet audit (durable record, post-shutdown) --------
    audits = []
    for i in range(N_PARTITIONS):
        n_events, seqs = read_match_seqs(bus_dirs[i])
        audits.append({
            "partition": i,
            "events": n_events,
            "stamped": len(seqs),
            "seq_audit": audit_seqs(seqs),
        })

    # -- throughput table (measured window only: warm-up excluded) ------
    lat_by_part = {}
    for i in range(N_PARTITIONS):
        js = sorted(
            (j for j in stitch["journeys"] if f"c{i}" in j["procs"]),
            key=lambda j: j["start"],
        )
        # The flight-recorder ring evicts oldest-first, so the tail of
        # the sorted list IS the measured window; take at most the
        # measured count from the end.
        keep = min(len(js), len(parts[i]) - warm_n[i])
        lat_by_part[i] = [j["duration_s"] for j in js[len(js) - keep:]]
    lat_all = [d for i in range(N_PARTITIONS) for d in lat_by_part[i]]
    procs_table = {}
    for i in range(N_PARTITIONS):
        gw, con = f"gw{i}", f"c{i}"
        measured_sent = drive[gw].get("sent", 0)
        consumed = results.get(con, {}).get("orders_consumed", 0)
        procs_table[gw] = {
            "role": "gateway", "partition": i,
            "orders_sent": measured_sent + warm[gw].get("sent", 0),
            "orders_measured": measured_sent,
            "orders_per_sec": round(measured_sent / drive_wall, 1),
            "grpc_codes": drive[gw].get("codes", {}),
            "rusage": results.get(gw, {}).get("rusage"),
        }
        procs_table[con] = {
            "role": "consumer", "partition": i,
            "orders_consumed": consumed,
            "orders_per_sec": round(measured_sent / drive_wall, 1),
            "feed": results.get(con, {}).get("feed"),
            "rusage": results.get(con, {}).get("rusage"),
        }
    part_counts = [len(p) for p in parts]
    table = {
        "drive_wall_s": round(drive_wall, 3),
        "warmup_orders": warm_n,
        "procs": procs_table,
        "fleet": {
            "orders": n_measured,
            "orders_per_sec": round(n_measured / drive_wall, 1),
        },
        # Routing-skew row (round 12): how evenly fleet.partition_of
        # spread this run's order flow. FLEET_r01 under crc32 showed a
        # 3.7x skew (625 vs 169); the row makes the spread a first-class
        # reviewed number instead of an accident buried in config.
        "imbalance": {
            "orders_per_partition": part_counts,
            "symbols_per_partition": sym_counts,
            "max_over_min_orders": round(
                max(part_counts) / max(1, min(part_counts)), 2
            ),
        },
        "e2e_latency_ms": {
            "samples": len(lat_all),
            "p50": _ms(pctl(lat_all, 50)),
            "p90": _ms(pctl(lat_all, 90)),
            "p99": _ms(pctl(lat_all, 99)),
            "per_partition": {
                str(i): {
                    "samples": len(lat_by_part[i]),
                    "p50": _ms(pctl(lat_by_part[i], 50)),
                    "p99": _ms(pctl(lat_by_part[i], 99)),
                }
                for i in range(N_PARTITIONS)
            },
        },
    }

    feed_states = [
        results.get(f"c{i}", {}).get("feed") or {}
        for i in range(N_PARTITIONS)
    ]
    checks = {
        "all_members_ready": len(results) == 2 * N_PARTITIONS,
        "all_members_exited_clean": all(
            r.get("exit_code") == 0 for r in results.values()
        ),
        "all_members_healthy": (
            rollup["polls"] >= 4 and rollup["unhealthy_polls"] == 0
            and rollup["fetch_errors"] == 0
        ),
        "zero_degradations": (
            rollup["degraded_polls"] == 0
            and accepted == n_orders
            and not any(
                "transport_error" in d
                for phase in (warm, drive) for d in phase.values()
            )
        ),
        "all_partitions_drained": all(drained) and all(warm_drained),
        "exactly_once_fleet": all(
            a["seq_audit"]["dupes"] == 0 and a["seq_audit"]["gaps"] == 0
            for a in audits
        ) and all(
            f.get("dupes") == 0 and f.get("gaps") == 0 for f in feed_states
        ),
        "stitched_per_partition": all(n >= 1 for n in per_part_stitched),
        "merge_roundtrip": merge_roundtrip_ok,
        "merge_lossless": (
            merged_consumed == member_consumed == float(accepted)
            and accepted > 0
        ),
        "fleet_payload_serves": (
            payload.get("enabled") is True
            and "exposition" in (payload.get("metrics") or {})
        ),
    }
    verdict = {
        "schema": SCHEMA,
        "config": {
            "seed": args.seed,
            "seconds": args.seconds,
            "n_steps": n_steps,
            "frames": len(frames),
            "orders": n_orders,
            "partitions": N_PARTITIONS,
            "orders_per_partition": [len(p) for p in parts],
            "symbols_per_partition": sym_counts,
            "drive": {
                "mode": "open-loop" if args.rate > 0 else "closed-loop",
                "rate_per_sec": args.rate if args.rate > 0 else None,
                "batch_n": args.drive_batch,
                "scheduler": (
                    "gome_tpu.obs.capacity.OpenLoopSchedule"
                    if args.rate > 0 else None
                ),
                "note": (
                    "orders_per_sec in this verdict reflects the CHOSEN "
                    "offered drive rate, not fleet capacity — the "
                    "measured saturation knee lives in CAPACITY_r01.json"
                ),
            },
            "engine": {
                "n_slots": N_LANES, "max_t": T_BINS,
                "cap": 64, "max_fills": 8, "dtype": "int64",
            },
        },
        "table": table,
        "rollup": rollup,
        "stitch": {
            "traces": stitch["traces"],
            "joined": stitch["joined"],
            "per_partition": per_part_stitched,
            "offsets_s": {
                k: round(v, 6) for k, v in stitch["offsets"].items()
            },
        },
        "merge": {
            "families": len(merged),
            "roundtrip_identical": merge_roundtrip_ok,
            "orders_consumed_total": {
                "merged": merged_consumed,
                "sum_of_members": member_consumed,
                "grpc_accepted": accepted,
            },
        },
        "seq": {"partitions": audits},
        "members": {
            name: {
                "exit_code": r.get("exit_code"),
                "role": r.get("role"),
                "partition": r.get("partition"),
            }
            for name, r in results.items()
        },
        "checks": checks,
        "pass": all(checks.values()),
    }
    write_json(args.out, verdict)
    status = "PASS" if verdict["pass"] else "FAIL"
    print(f"fleet: {status} -> {args.out}")
    print(f"fleet: {n_measured} measured orders over {N_PARTITIONS} "
          f"partitions in {drive_wall:.2f}s = "
          f"{n_measured / drive_wall:.0f} orders/s "
          f"(e2e p50 {table['e2e_latency_ms']['p50']} ms over "
          f"{len(lat_all)} stitched journeys)")
    for name, ok in checks.items():
        print(f"  [{'ok' if ok else 'BREACH'}] {name}")
    return 0 if verdict["pass"] else 1


def _ms(s: float | None) -> float | None:
    return None if s is None else round(s * 1e3, 3)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seconds", type=int, default=30,
                    help="drill scale knob: sim steps = seconds*8 (clamped)")
    ap.add_argument("--rate", type=float, default=600.0,
                    help="open-loop offered drive rate, aggregate "
                         "orders/s (0 = legacy closed-loop fire-hose); "
                         "default sits below the CAPACITY_r01 knee so "
                         "the drill measures a healthy fleet")
    ap.add_argument("--drive-batch", type=int, default=0,
                    help="orders per DoOrderBatch (default: 64 "
                         "rate-controlled, 1024 closed-loop)")
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--out", default="FLEET_r01.json",
                    help="verdict JSON path (parent mode)")
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: fresh tempdir)")
    # worker mode (internal)
    ap.add_argument("--worker", choices=("gateway", "consumer"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--bus-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--resp-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--partition", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--result", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if not args.drive_batch:
        args.drive_batch = 64 if args.rate > 0 else DRIVE_BATCH_N
    if args.worker == "gateway":
        return run_gateway_worker(args)
    if args.worker == "consumer":
        return run_consumer_worker(args)
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())

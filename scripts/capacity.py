#!/usr/bin/env python
"""Capacity observatory sweep (ISSUE 17): drive the matching engine to
its knee with an OPEN-LOOP offered-rate ladder and say where the time
goes.

Every prior latency artifact in this repo was measured closed-loop — the
driver waited for the service before sending the next order, so under
saturation the arrival process silently slowed down and queueing delay
never reached the percentiles (coordinated omission). This sweep fixes
the arrival model: each order has an *intended* send time from a fixed
:class:`gome_tpu.obs.capacity.OpenLoopSchedule` at the offered rate, the
driver sends on that clock (immediately when behind — the backlog is
charged to latency, never forgiven), and every per-order latency is
``completion - intended`` recorded into a mergeable
:class:`~gome_tpu.obs.capacity.LogHistogram`.

Two targets, one verdict schema (``gome-capacity-verdict-v1``):

  * default — the single-process service stack (gateway step -> memory
    bus -> consumer -> engine, the soak/bench pipeline) with exact
    per-frame completion times. Fast enough for CI: the ninth tier-1
    gate runs this as a ~10 s smoke ladder.
  * ``--fleet`` — the real 2-gateway x 2-consumer subprocess fleet from
    scripts/fleet_drill.py (same workers, same file bus + RESP marker
    store), driven per-partition over columnar ``DoOrderBatch`` streams
    routed by ``fleet.partition_of``. Completion times come from polling
    each consumer's ``gome_orders_consumed_total`` (per-partition FIFO
    inverts the counter into per-order completions, interpolated between
    samples). The committed CAPACITY_r01.json is produced by this mode.

Each ladder point records offered vs delivered rate, corrected AND
legacy closed-loop percentiles, an exactly-once audit (match-queue seq
dupes/gaps + conservation), and a bottleneck-attribution table joining
the driver's own measurements (send backlog, batch accumulation, admit
RPC wall) with the fleet's telemetry (``gome_stage_seconds`` deltas,
``gome_bus_depth`` Little's-law wait, timeline RSS/nivcsw). The knee is
the first point where delivered/offered < 0.98 or the corrected p99
blows its budget; the verdict names the saturated stage there.

Usage:
    python scripts/capacity.py --seconds 10 --out capacity_smoke.json
    python scripts/capacity.py --fleet --window 4 --out CAPACITY_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Must be set before anything imports jax (fleet workers inherit it).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gome_tpu.obs.capacity import (  # noqa: E402 - after the platform pin
    SCHEMA,
    LogHistogram,
    OpenLoopSchedule,
    attribution_check,
    find_knee,
    monotone_ladder,
    saturated_stage,
)

#: Histogram geometry shared by every recorder in one sweep (merge
#: requires identical params; 1% relative error, 1 us .. 10 min).
HIST_KW = dict(rel_err=0.01, min_value=1e-6, max_value=600.0)


class _CrossingFlow:
    """Bounded-book sweep flow (single mode): round-robin symbols,
    alternating buy/sell limit pairs at ONE price so every pair trades
    and resting depth stays ~1 per symbol. A capacity sweep must hold
    frame geometry stationary — ``bench._MixedFlow``'s depth walk
    ratchets the packed-book shape mid-ladder and every ratchet is a
    trace+compile stall that would masquerade as a knee."""

    def __init__(self, n_symbols: int):
        import numpy as np

        self.np = np
        self.n_symbols = n_symbols
        self.i0 = 0

    def frame(self, n: int) -> dict:
        np = self.np
        i = self.i0 + np.arange(n, dtype=np.int64)
        self.i0 += n
        sym = (i % self.n_symbols).astype(np.uint32)
        return dict(
            n=n,
            action=np.ones(n, np.uint8),
            side=((i // self.n_symbols) % 2).astype(np.uint8),
            kind=np.zeros(n, np.uint8),
            price=np.full(n, 100_000_000, np.int64),
            volume=np.ones(n, np.int64),
            symbol_idx=sym,
            uuid_idx=(i % 256).astype(np.uint32),
            oids=np.char.add("o", i.astype("U12")).astype("S"),
        )


def steady_delivered(done_t: list, window_end: float, batch_n: int,
                     t0: float) -> float:
    """Delivered rate in steady state: completions per second between
    the FIRST and LAST in-window completion. Counting from t0 (or to
    window_end) would charge the pipeline-fill ramp and the in-flight
    tail against throughput — at a short window that undercount alone
    fakes a knee."""
    in_win = [d for d in done_t if d <= window_end]
    if len(in_win) >= 3 and in_win[-1] > in_win[0]:
        return (len(in_win) - 1) * batch_n / (in_win[-1] - in_win[0])
    elapsed = max(window_end - t0, 1e-9)
    return len(in_win) * batch_n / elapsed


#: Tracer stages that measure WAITING (overlapping across in-flight
#: orders), not a resource being busy — their span-sum over wall time is
#: not an occupancy, so they never compete for "saturated stage".
_WAIT_STAGES = frozenset({"ingress", "enqueue", "batch_wait", "bus_transit"})


def _hist() -> LogHistogram:
    return LogHistogram(**HIST_KW)


def write_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def geometric_ladder(lo: float, hi: float, k: int) -> list[float]:
    """k strictly increasing rates from lo to hi, geometric spacing."""
    if k < 2:
        return [hi]
    f = (hi / lo) ** (1.0 / (k - 1))
    return [lo * f**i for i in range(k)]


def _lat_summary(h: LogHistogram) -> dict:
    return h.summary(qs=(0.5, 0.9, 0.99, 0.999))


# -- verdict assembly (shared by both modes) ---------------------------------


def build_verdict(mode: str, config: dict, points: list[dict],
                  delivered_floor: float, p99_budget_s: float,
                  extra_checks: dict | None = None) -> dict:
    knee_idx, knee_reason = find_knee(
        points, delivered_floor=delivered_floor, p99_budget_s=p99_budget_s
    )
    knee: dict = {"found": knee_idx is not None}
    if knee_idx is not None:
        kp = points[knee_idx]
        knee.update({
            "index": knee_idx,
            "reason": knee_reason,
            "offered_per_sec": kp["offered_per_sec"],
            "delivered_per_sec": kp["delivered_per_sec"],
            "corrected_p99_s": kp["corrected"]["p99_s"],
            "saturated_stage": saturated_stage(
                kp["attribution"]["rows"]
            ),
            "attribution_frac_err": kp["attribution"]["frac_err"],
        })
    checks = {
        "monotone_ladder": monotone_ladder(points),
        "ladder_has_5_points": len(points) >= 5,
        "knee_found": knee_idx is not None,
        "exactly_once_all_points": all(
            p["exactly_once"]["dupes"] == 0
            and p["exactly_once"]["gaps"] == 0
            and p["exactly_once"]["drained"]
            for p in points
        ),
        "corrected_recorded_all_points": all(
            p["corrected"]["count"] == p["sent"] for p in points
        ),
        "attribution_rows_nonempty": all(
            p["attribution"]["rows"] for p in points
        ),
        "attribution_within_tol_at_knee": (
            knee_idx is not None
            and points[knee_idx]["attribution"]["within_tol"]
        ),
    }
    checks.update(extra_checks or {})
    return {
        "schema": SCHEMA,
        "mode": mode,
        "config": config,
        "ladder": points,
        "knee": knee,
        "checks": checks,
        "pass": all(checks.values()),
    }


def print_verdict(verdict: dict, out: str) -> None:
    status = "PASS" if verdict["pass"] else "FAIL"
    print(f"capacity: {status} -> {out}")
    for p in verdict["ladder"]:
        print(
            f"  offered {p['offered_per_sec']:8.1f}/s  delivered "
            f"{p['delivered_per_sec']:8.1f}/s ({p['delivered_frac']:.3f})  "
            f"corrected p50 {p['corrected']['p50_s'] * 1e3:7.1f}ms  "
            f"p99 {p['corrected']['p99_s'] * 1e3:8.1f}ms"
        )
    knee = verdict["knee"]
    if knee.get("found"):
        print(
            f"  knee @ {knee['offered_per_sec']:.1f}/s offered "
            f"({knee['reason']}); saturated stage: "
            f"{knee['saturated_stage']} "
            f"(attribution err {knee['attribution_frac_err']:.3f})"
        )
    for name, ok in verdict["checks"].items():
        print(f"  [{'ok' if ok else 'BREACH'}] {name}")


# ===========================================================================
# single-process mode (smoke ladder: CI's ninth gate, obs_snapshot capture)
# ===========================================================================


def _counter(name: str) -> int:
    from gome_tpu.utils.metrics import REGISTRY

    return int(REGISTRY.counter(name).value())


def _stage_snapshot() -> dict:
    """{stage: (count, sum_s)} from the armed tracer's histograms."""
    from gome_tpu.utils.trace import TRACER

    return {
        s: (v["count"], v["sum"]) for s, v in TRACER.stage_summary().items()
    }


def run_single_point(engine, bus, consumer, flow, symbols,
                     rate: float, window_s: float, batch_n: int) -> dict:
    """One open-loop load point against the in-process pipeline.

    The consumer is co-operative (no thread of its own): while the
    driver is ahead of schedule it drains completions; when it falls
    behind it publishes immediately and the backlog lands in the
    corrected latency, exactly as the open-loop contract demands."""
    from bench import _svc_gateway_step

    n_frames = max(2, int(rate * window_s) // batch_n)
    frames = [flow.frame(batch_n) for _ in range(n_frames)]
    n_point = n_frames * batch_n

    stage0 = _stage_snapshot()
    fail0 = _counter("gome_consumer_step_failures_total")
    ev_off = bus.match_queue.end_offset()

    corrected, closed = _hist(), _hist()
    sched = OpenLoopSchedule(rate, t0=time.perf_counter())
    pub_t: list[float] = []
    done_t: list[float] = []
    backlog: list[float] = []
    gw_wall = 0.0

    def drain_step() -> int:
        n = consumer.run_once()
        if n:
            now = time.perf_counter()
            for _ in range(n // batch_n):
                done_t.append(now)
        return n

    for fi, cols in enumerate(frames):
        due = sched.batch_due(fi * batch_n, batch_n)
        while True:
            now = time.perf_counter()
            if now >= due:
                break
            if not drain_step():
                time.sleep(min(0.0005, due - now))
        actual = time.perf_counter()
        backlog.append(actual - due)
        pub_t.append(actual)
        t_gw = time.perf_counter()
        _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
        gw_wall += time.perf_counter() - t_gw
        drain_step()
    window_end = time.perf_counter()

    deadline = time.monotonic() + 120.0
    while len(done_t) < n_frames and time.monotonic() < deadline:
        if not drain_step():
            n = consumer.drain()
            now = time.perf_counter()
            for _ in range(n // batch_n):
                done_t.append(now)
            if len(done_t) < n_frames:
                time.sleep(0.0005)
    drained = len(done_t) >= n_frames

    # Per-order latency: FIFO frames; order j of frame f was intended at
    # sched.intended(f*batch_n + j) and completed at done_t[f].
    for f in range(min(len(done_t), n_frames)):
        d = done_t[f]
        for j in range(batch_n):
            corrected.record(max(d - sched.intended(f * batch_n + j), 0.0))
        closed.record(max(d - pub_t[f], 0.0), count=batch_n)

    elapsed_send = window_end - sched.t0
    delivered_per_sec = steady_delivered(
        done_t, window_end, batch_n, sched.t0
    )
    busy_s = max(done_t[-1] if done_t else window_end, window_end) - sched.t0

    # -- exactly-once: conservation + (if stamped) the seq audit ---------
    from gome_tpu.bus.colwire import decode_event_frame

    events, seqs = 0, []
    for m in bus.match_queue.read_from(ev_off, 1 << 20):
        for r in decode_event_frame(m.body).to_results():
            events += 1
            if r.seq is not None:
                seqs.append(r.seq)
    bus.match_queue.commit(bus.match_queue.end_offset())
    bus.match_queue.compact()
    bus.order_queue.compact()
    step_failures = _counter("gome_consumer_step_failures_total") - fail0
    consumed = len(done_t) * batch_n
    exactly_once = {
        "method": "conservation+seq",
        "sent": n_point,
        "consumed": consumed,
        "events": events,
        "dupes": 0,
        "gaps": (n_point - consumed) + step_failures,
        "drained": drained and step_failures == 0,
    }

    # -- attribution -----------------------------------------------------
    stage1 = _stage_snapshot()
    mean_backlog = sum(backlog) / len(backlog) if backlog else 0.0
    in_pipeline = [
        done_t[f] - pub_t[f] for f in range(min(len(done_t), n_frames))
    ]
    in_pipeline_mean = (
        sum(in_pipeline) / len(in_pipeline) if in_pipeline else 0.0
    )
    rows = [
        {
            "stage": "arrival_accumulation",
            "seconds_per_order": sched.accumulation_mean(batch_n),
            "utilization": None,
            "source": "analytic (batch_n-1)/(2*rate)",
        },
        {
            "stage": "send_backlog",
            "seconds_per_order": mean_backlog,
            "utilization": None,
            "source": "driver (actual publish - intended last-of-frame)",
        },
        {
            "stage": "gateway_step",
            "seconds_per_order": gw_wall / max(n_frames, 1),
            "utilization": gw_wall / busy_s if busy_s > 0 else 0.0,
            "source": "driver (publish call wall per frame)",
        },
    ]
    stage_total = 0.0
    for stage in sorted(set(stage0) | set(stage1)):
        c0, s0 = stage0.get(stage, (0, 0.0))
        c1, s1 = stage1.get(stage, (0, 0.0))
        dc, ds = c1 - c0, s1 - s0
        if dc <= 0:
            continue
        per_order = ds / dc  # an order rides its whole frame's span
        stage_total += per_order
        busy_like = stage not in _WAIT_STAGES
        rows.append({
            "stage": stage,
            "seconds_per_order": per_order,
            "utilization": (
                ds / busy_s if busy_s > 0 else 0.0
            ) if busy_like else None,
            "source": "tracer gome_stage_seconds delta / spans",
        })
    rows.append({
        "stage": "bus_wait",
        "seconds_per_order": max(
            in_pipeline_mean - gw_wall / max(n_frames, 1) - stage_total, 0.0
        ),
        "utilization": None,
        "source": "residual (in-pipeline mean minus processing stages)",
    })
    attr = attribution_check(rows, corrected.mean(), tol=0.05)
    attr["rows"] = rows

    return {
        "offered_per_sec": rate,
        "delivered_per_sec": round(delivered_per_sec, 2),
        "delivered_frac": round(
            delivered_per_sec / rate if rate > 0 else 0.0, 4
        ),
        "sent": n_point,
        "frames": n_frames,
        "batch_n": batch_n,
        "window_s": round(elapsed_send, 3),
        "send_backlog_s_mean": round(mean_backlog, 6),
        "corrected": _lat_summary(corrected),
        "closed_loop": _lat_summary(closed),
        "exactly_once": exactly_once,
        "attribution": attr,
    }


def run_single_sweep(seconds: float = 10.0, points: int = 6,
                     symbols: int = 32, cap: int = 128, batch_n: int = 256,
                     pipeline: int = 2, seed: int = 17,
                     delivered_floor: float = 0.98,
                     p99_budget_s: float = 1.0,
                     rates: list[float] | None = None) -> dict:
    """The smoke ladder: calibrate, sweep, verdict. Importable (the
    obs_snapshot capture and the CI gate call this in-process)."""
    import jax
    import jax.numpy as jnp

    from bench import _svc_warmup
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.utils.metrics import Registry
    from gome_tpu.utils.trace import TRACER, FlightRecorder

    kernel = "pallas" if jax.default_backend() == "tpu" else "scan"
    engine = MatchEngine(
        config=BookConfig(cap=cap, max_fills=16, dtype=jnp.int32),
        n_slots=symbols, max_t=32, kernel=kernel,
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=pipeline,
    )
    flow = _CrossingFlow(symbols)
    sym_names = [f"sym{i}" for i in range(symbols)]

    t0 = time.perf_counter()
    _svc_warmup(engine, consumer, bus, lambda: flow.frame(batch_n), sym_names)
    warm_s = time.perf_counter() - t0

    # Private registry: the sweep's stage histograms must not pollute
    # (or be polluted by) anything else in the process.
    TRACER.install(FlightRecorder(keep_n=8), registry=Registry())
    try:
        # -- closed-loop calibration: the ladder needs a scale ----------
        from bench import _svc_gateway_step

        cal_frames = [flow.frame(batch_n) for _ in range(24)]
        t0 = time.perf_counter()
        done = 0
        for cols in cal_frames:
            _svc_gateway_step(cols, sym_names, engine.pre_pool,
                              bus.order_queue)
            done += consumer.run_once()
        done += consumer.drain()
        cal_s = time.perf_counter() - t0
        cal_rate = done / cal_s
        bus.match_queue.commit(bus.match_queue.end_offset())
        bus.match_queue.compact()
        bus.order_queue.compact()

        if rates is None:
            rates = geometric_ladder(
                0.30 * cal_rate, 1.60 * cal_rate, points
            )
        window_s = max(0.8, seconds / len(rates))
        ladder = [
            run_single_point(
                engine, bus, consumer, flow, sym_names,
                rate=r, window_s=window_s, batch_n=batch_n,
            )
            for r in rates
        ]
    finally:
        TRACER.disable()

    config = {
        "seconds": seconds,
        "points": len(rates),
        "window_s": round(window_s, 3),
        "batch_n": batch_n,
        "symbols": symbols,
        "cap": cap,
        "pipeline_depth": pipeline,
        "seed": seed,
        "kernel": kernel,
        "platform": jax.default_backend(),
        "warmup_s": round(warm_s, 3),
        "calibration_orders_per_sec": round(cal_rate, 1),
        "delivered_floor": delivered_floor,
        "p99_budget_s": p99_budget_s,
        "histogram": HIST_KW,
        "arrival_model": (
            "open-loop fixed schedule: intended_i = t0 + (i+1)/rate; "
            "latency charged from intended time"
        ),
    }
    return build_verdict(
        "single", config, ladder, delivered_floor, p99_budget_s
    )


# ===========================================================================
# fleet mode (the real 2x2 subprocess fleet; source of CAPACITY_r01.json)
# ===========================================================================

N_SYMBOLS_FLEET = 16  # <= worker N_LANES so no partition overflows slots


def synth_requests(n: int, base: int, fd) -> list[list]:
    """Bounded-book crossing flow, routed like production: order i takes
    symbol i % N_SYMBOLS_FLEET, and successive orders on one symbol
    alternate buy/sale at one price so each pair trades and the book
    stays ~empty (the sweep must measure rate, not book growth). Returns
    per-partition lists of (global_index, OrderRequest); global index
    preserves the open-loop schedule's arrival order."""
    from gome_tpu.api import order_pb2 as pb

    parts: list[list] = [[] for _ in range(fd.N_PARTITIONS)]
    for i in range(base, base + n):
        s = i % N_SYMBOLS_FLEET
        symbol = f"cap{s:03d}"
        req = pb.OrderRequest(
            uuid=f"u{s:03d}",
            oid=f"o{i:010d}",
            symbol=symbol,
            transaction=(i // N_SYMBOLS_FLEET) % 2,
            price=100.0,
            volume=1.0,
            kind=0,
        )
        parts[fd.partition_of(symbol)].append((i - base, req))
    return parts


_CONSUMED_RE = re.compile(r"^gome_orders_consumed_total\S* ([0-9eE+.\-]+)$",
                          re.MULTILINE)
_DEPTH_RE = re.compile(
    r'gome_bus_depth\{[^}]*queue="doOrder"[^}]*\} ([0-9eE+.\-]+)'
)
_STAGE_SUM_RE = re.compile(
    r'gome_stage_seconds_sum\{[^}]*stage="([^"]+)"[^}]*\} ([0-9eE+.\-]+)'
)
_STAGE_CNT_RE = re.compile(
    r'gome_stage_seconds_count\{[^}]*stage="([^"]+)"[^}]*\} ([0-9eE+.\-]+)'
)


def _fetch_text(url: str, timeout_s: float = 3.0) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode()


def _parse_consumed(text: str) -> int:
    m = _CONSUMED_RE.search(text)
    return int(float(m.group(1))) if m else 0


def _parse_depth(text: str) -> float:
    m = _DEPTH_RE.search(text)
    return float(m.group(1)) if m else 0.0


def _parse_stages(text: str) -> dict:
    sums = {m.group(1): float(m.group(2))
            for m in _STAGE_SUM_RE.finditer(text)}
    cnts = {m.group(1): float(m.group(2))
            for m in _STAGE_CNT_RE.finditer(text)}
    return {s: (cnts.get(s, 0.0), sums[s]) for s in sums}


class ConsumerSampler(threading.Thread):
    """Polls each consumer's /metrics on one thread, recording
    (perf_counter t, orders consumed, doOrder bus depth) triples — the
    completion-inversion and Little's-law feed for one load point."""

    def __init__(self, urls: dict, interval_s: float = 0.025):
        super().__init__(name="capacity-sampler", daemon=True)
        self.urls = urls
        self.interval_s = interval_s
        self.samples: dict = {name: [] for name in urls}  # single-writer: run()
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            for name, url in self.urls.items():
                try:
                    text = _fetch_text(url + "/metrics", timeout_s=2.0)
                except Exception:
                    continue
                self.samples[name].append((
                    time.perf_counter(),
                    _parse_consumed(text),
                    _parse_depth(text),
                ))
            self._halt.wait(self.interval_s)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=10)


def _interp_consumed(samples: list, t: float) -> float:
    """Consumed counter value at time t, linear between samples."""
    if not samples:
        return 0.0
    prev = samples[0]
    if t <= prev[0]:
        return float(prev[1])
    for s in samples[1:]:
        if s[0] >= t:
            t0, c0 = prev[0], prev[1]
            t1, c1 = s[0], s[1]
            if t1 <= t0:
                return float(c1)
            return c0 + (c1 - c0) * (t - t0) / (t1 - t0)
        prev = s
    return float(prev[1])


def _completion_times(samples: list, c_base: int, n: int) -> list[float]:
    """Invert the consumed counter into per-order completion times:
    per-partition FIFO means order rank r completes when the counter
    crosses c_base + r + 1; interpolate within each sample interval."""
    comp = [0.0] * n
    filled = 0
    prev_t, prev_c = samples[0][0], samples[0][1]
    for t, c, _ in samples[1:]:
        if c > prev_c:
            lo = max(prev_c, c_base)
            hi = min(c, c_base + n)
            for k in range(lo, hi):
                frac = (k - prev_c + 0.5) / (c - prev_c)
                comp[k - c_base] = prev_t + frac * (t - prev_t)
                filled += 1
        prev_t, prev_c = t, c
    last_t = samples[-1][0]
    for r in range(n):
        if comp[r] == 0.0:
            comp[r] = last_t  # sampler tail raced the drain; charge its end
    return comp


def _drive_fleet_partition(target: str, items: list, sched, batch_n: int,
                           out: dict) -> None:
    """Open-loop drive of one partition: batches of its orders, each
    sent at the intended time of its LAST order (send immediately when
    behind — the backlog is measured, not forgiven)."""
    import grpc

    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.api.service import OrderStub

    batches = []
    accepted = 0
    try:
        with grpc.insecure_channel(target) as channel:
            stub = OrderStub(channel)
            for i in range(0, len(items), batch_n):
                chunk = items[i:i + batch_n]
                due = sched.intended(chunk[-1][0])
                now = time.perf_counter()
                if now < due:
                    time.sleep(due - now)
                t_send = time.perf_counter()
                resp = stub.DoOrderBatch(
                    pb.OrderBatchRequest(orders=[r for _, r in chunk]),
                    timeout=60,
                )
                t_ret = time.perf_counter()
                accepted += resp.accepted
                batches.append({
                    "first_rank": i,
                    "n": len(chunk),
                    "due": due,
                    "t_send": t_send,
                    "t_ret": t_ret,
                    "accepted": resp.accepted,
                })
    except grpc.RpcError as exc:  # pragma: no cover - transport breach
        out["transport_error"] = str(exc)
    out["batches"] = batches
    out["sent"] = len(items)
    out["accepted"] = accepted


def _await_fleet_drained(sampler_urls: dict, expect: dict,
                         timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            ok = all(
                _parse_consumed(_fetch_text(url + "/metrics")) >= expect[name]
                for name, url in sampler_urls.items()
            )
            if ok:
                return True
        except Exception:
            pass
        time.sleep(0.1)
    return False


def _timeline_tail(url: str) -> dict:
    try:
        doc = _fetch_text(url + "/timeline")
        samples = json.loads(doc).get("samples") or []
        if not samples:
            return {}
        last = samples[-1]
        return {
            "rss_bytes": last.get("rss_bytes"),
            "nivcsw": last.get("nivcsw"),
            "cpu_utime_s": last.get("cpu_utime_s"),
        }
    except Exception:
        return {}


def run_fleet_point(ctx: dict, rate: float, window_s: float, batch_n: int,
                    oid_base: int) -> tuple[dict, int]:
    """One open-loop load point against the live 2x2 fleet. Returns the
    ladder-point dict and the next oid base."""
    fd = ctx["fd"]
    n_point = max(batch_n * fd.N_PARTITIONS, int(rate * window_s))
    parts = synth_requests(n_point, oid_base, fd)
    consumed0 = {
        name: _parse_consumed(_fetch_text(url + "/metrics"))
        for name, url in ctx["consumers"].items()
    }
    stages0 = {
        name: _parse_stages(_fetch_text(url + "/metrics"))
        for name, url in ctx["consumers"].items()
    }

    sampler = ConsumerSampler(ctx["consumers"], interval_s=0.025)
    sampler.start()
    time.sleep(0.08)  # at least one pre-drive sample per member

    sched = OpenLoopSchedule(rate, t0=time.perf_counter())
    drive: dict[int, dict] = {i: {} for i in range(fd.N_PARTITIONS)}
    threads = [
        threading.Thread(
            target=_drive_fleet_partition,
            args=(ctx["gw_targets"][i], parts[i], sched, batch_n, drive[i]),
        )
        for i in range(fd.N_PARTITIONS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    window_end = sched.t0 + n_point / rate

    expect = {
        f"c{i}": consumed0[f"c{i}"] + len(parts[i])
        for i in range(fd.N_PARTITIONS)
    }
    drained = _await_fleet_drained(
        ctx["consumers"], expect, timeout_s=max(120.0, 4 * window_s)
    )
    time.sleep(0.1)  # let the sampler catch the final counter value
    sampler.stop()

    # -- per-order latency via counter inversion -------------------------
    per_part_hists = []
    corrected, closed = _hist(), _hist()
    delivered_rates = []
    depth_means = {}
    little_wait = []
    point_orders = []
    for i in range(fd.N_PARTITIONS):
        name = f"c{i}"
        samples = sampler.samples[name]
        if not samples or not parts[i]:
            per_part_hists.append((_hist(), _hist()))
            continue
        comp = _completion_times(samples, consumed0[name], len(parts[i]))
        pc, pl = _hist(), _hist()
        send_t = {}
        for b in drive[i].get("batches", []):
            for r in range(b["first_rank"], b["first_rank"] + b["n"]):
                send_t[r] = b["t_send"]
        for r, (gi, _req) in enumerate(parts[i]):
            pc.record(max(comp[r] - sched.intended(gi), 0.0))
            pl.record(max(comp[r] - send_t.get(r, sched.t0), 0.0))
        per_part_hists.append((pc, pl))
        # Steady-state delivered: slope of the consumed counter between
        # its first and last increase. Counting from t0 would charge
        # batch accumulation; cutting at window_end would drop the
        # in-flight tail — either one fakes a knee at low load.
        inc = [
            k for k in range(1, len(samples))
            if samples[k][1] > samples[k - 1][1]
        ]
        if (len(inc) >= 2
                and samples[inc[-1]][1] > samples[inc[0]][1]
                and samples[inc[-1]][0] > samples[inc[0]][0]):
            delivered_rates.append(
                (samples[inc[-1]][1] - samples[inc[0]][1])
                / (samples[inc[-1]][0] - samples[inc[0]][0])
            )
        else:
            delivered_rates.append(
                len(parts[i]) / max(comp[-1] - sched.t0, 1e-9)
            )
        depths = [d for (t, _c, d) in samples if t <= comp[-1]]
        depth_means[name] = (
            sum(depths) / len(depths) if depths else 0.0
        )
        span = max(comp[-1] - sched.t0, 1e-9)
        little_wait.append(
            (len(parts[i]),
             depth_means[name] / (len(parts[i]) / span))
        )
        point_orders.append((i, comp))
    for pc, pl in per_part_hists:
        corrected.merge(pc)
        closed.merge(pl)
    # Cross-process merge proof: the merged recorder must equal the sum
    # of its parts (integer-count state makes this exact).
    merge_lossless = corrected.count == sum(
        pc.count for pc, _ in per_part_hists
    )

    elapsed_offer = window_end - sched.t0
    delivered_per_sec = sum(delivered_rates)

    # -- exactly-once: cumulative match-queue seq audit -------------------
    audits = []
    events_total = 0
    for i in range(fd.N_PARTITIONS):
        n_events, seqs = fd.read_match_seqs(ctx["bus_dirs"][i])
        audit = fd.audit_seqs(seqs)
        events_total += n_events
        audits.append({
            "partition": i,
            "events": n_events,
            "stamped": len(seqs),
            "dupes": audit.get("dupes", 0),
            "gaps": audit.get("gaps", 0),
        })
    accepted = sum(drive[i].get("accepted", 0)
                   for i in range(fd.N_PARTITIONS))
    exactly_once = {
        "method": "matchfeed seq audit (cumulative) + conservation",
        "sent": n_point,
        "accepted": accepted,
        "events": events_total,
        "dupes": sum(a["dupes"] for a in audits),
        "gaps": sum(a["gaps"] for a in audits),
        "drained": drained and accepted == n_point,
        "partitions": audits,
    }

    # -- attribution ------------------------------------------------------
    stages1 = {
        name: _parse_stages(_fetch_text(url + "/metrics"))
        for name, url in ctx["consumers"].items()
    }
    all_batches = [
        b for i in range(fd.N_PARTITIONS)
        for b in drive[i].get("batches", [])
    ]
    n_sent_batched = sum(b["n"] for b in all_batches) or 1
    # Exact pre-send decomposition: mean over orders of (due - intended)
    # is the accumulation wait; (t_send - due) is the backlog.
    accum = 0.0
    for i in range(fd.N_PARTITIONS):
        for b in drive[i].get("batches", []):
            lo = b["first_rank"]
            for r in range(lo, lo + b["n"]):
                gi = parts[i][r][0]
                accum += b["due"] - sched.intended(gi)
    accum /= n_sent_batched
    backlog = sum(
        (b["t_send"] - b["due"]) * b["n"] for b in all_batches
    ) / n_sent_batched
    # An order's latency path only includes admission up to ITS slot of
    # the serial per-order scalar path inside the RPC — the mean slot is
    # (n+1)/2n of the wall; charging the full wall per order would
    # double-count the tail of every batch.
    admit = sum(
        (b["t_ret"] - b["t_send"]) * (b["n"] + 1) / 2
        for b in all_batches
    ) / n_sent_batched
    admit_busy = [
        sum(b["t_ret"] - b["t_send"] for b in drive[i].get("batches", []))
        for i in range(fd.N_PARTITIONS)
    ]
    busy_end = max(
        (comp[-1] for _i, comp in point_orders), default=window_end
    )
    busy_s = max(busy_end - sched.t0, 1e-9)
    bus_wait = (
        sum(n * w for n, w in little_wait) / sum(n for n, _ in little_wait)
        if little_wait else 0.0
    )
    rows = [
        {
            "stage": "arrival_accumulation",
            "seconds_per_order": accum,
            "utilization": None,
            "source": "exact (batch due - per-order intended)",
        },
        {
            "stage": "send_backlog",
            "seconds_per_order": backlog,
            "utilization": None,
            "source": "driver (batch send - batch due)",
        },
        {
            "stage": "admit",
            "seconds_per_order": admit,
            "utilization": max(w / busy_s for w in admit_busy),
            "source": "driver (DoOrderBatch RPC wall, mean-slot share)",
        },
        {
            "stage": "bus_wait",
            "seconds_per_order": bus_wait,
            "utilization": None,
            "source": "Little's law on sampled gome_bus_depth{doOrder}",
        },
    ]
    stage_names = sorted({
        s for d in stages1.values() for s in d
    })
    for stage in stage_names:
        dc = ds = 0.0
        per_member_busy = []
        for name in ctx["consumers"]:
            c0, s0 = stages0.get(name, {}).get(stage, (0.0, 0.0))
            c1, s1 = stages1.get(name, {}).get(stage, (0.0, 0.0))
            dc += c1 - c0
            ds += s1 - s0
            per_member_busy.append((s1 - s0) / busy_s)
        if dc <= 0:
            continue
        # Wait-like stages (queue transit etc.) overlap across in-flight
        # orders — their span-sum over wall is occupancy of nothing, so
        # they don't compete for "saturated stage".
        busy_like = stage not in _WAIT_STAGES
        rows.append({
            "stage": stage,
            "seconds_per_order": ds / dc,
            "utilization": max(per_member_busy) if busy_like else None,
            "source": "consumer gome_stage_seconds delta / spans",
        })
    attr = attribution_check(rows, corrected.mean(), tol=0.05)
    attr["rows"] = rows
    attr["note"] = (
        "bus_wait (Little's law) and the consumer stage spans overlap by "
        "up to one in-flight batch; the sum check tolerates it at 5%"
    )

    host = {
        name: _timeline_tail(url) for name, url in ctx["consumers"].items()
    }
    point = {
        "offered_per_sec": rate,
        "delivered_per_sec": round(delivered_per_sec, 2),
        "delivered_frac": round(delivered_per_sec / rate, 4),
        "sent": n_point,
        "orders_per_partition": [len(p) for p in parts],
        "batch_n": batch_n,
        "window_s": round(elapsed_offer, 3),
        "send_backlog_s_mean": round(backlog, 6),
        "corrected": _lat_summary(corrected),
        "closed_loop": _lat_summary(closed),
        "merge_lossless": merge_lossless,
        "exactly_once": exactly_once,
        "attribution": attr,
        "host": host,
        "bus_depth_mean": {
            k: round(v, 2) for k, v in depth_means.items()
        },
    }
    return point, oid_base + n_point


def run_fleet_sweep(args) -> dict:
    """Start the real 2x2 fleet (fleet_drill's own workers), warm it,
    calibrate, run the ladder, and assemble the verdict."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "fleet_drill", os.path.join(REPO, "scripts", "fleet_drill.py")
    )
    fd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fd)

    work = args.workdir or tempfile.mkdtemp(prefix="gome-capacity-")
    os.makedirs(work, exist_ok=True)
    drill_py = os.path.join(REPO, "scripts", "fleet_drill.py")

    resp = None
    workers: dict = {}
    try:
        resp = fd.start_respserver(work)
        bus_dirs = []
        for i in range(fd.N_PARTITIONS):
            bus_dir = os.path.join(work, f"p{i}", "bus")
            os.makedirs(bus_dir, exist_ok=True)
            bus_dirs.append(bus_dir)
            for role in ("consumer", "gateway"):
                name = ("c" if role == "consumer" else "gw") + str(i)
                workers[name] = fd.Worker(name, [
                    sys.executable, drill_py,
                    "--worker", role,
                    "--bus-dir", bus_dir,
                    "--resp-port", str(resp.resp_port),
                    "--partition", str(i),
                    "--result", os.path.join(work, f"{name}_result.json"),
                ])
        for name, w in workers.items():
            w.await_ready()
            print(f"capacity: {name} ready (ops={w.ports['ops']}, "
                  f"grpc={w.ports['grpc']})")
        ctx = {
            "fd": fd,
            "bus_dirs": bus_dirs,
            "gw_targets": {
                i: f"127.0.0.1:{workers[f'gw{i}'].ports['grpc']}"
                for i in range(fd.N_PARTITIONS)
            },
            "consumers": {
                f"c{i}": f"http://127.0.0.1:{workers[f'c{i}'].ports['ops']}"
                for i in range(fd.N_PARTITIONS)
            },
        }

        # -- warm-up: force the compiles off the measured ladder ---------
        oid_base = 0
        warm_parts = synth_requests(128, oid_base, fd)
        oid_base += 128
        warm_sched = OpenLoopSchedule(1e9, t0=time.perf_counter())
        warm_out: dict[int, dict] = {i: {} for i in range(fd.N_PARTITIONS)}
        for i in range(fd.N_PARTITIONS):
            _drive_fleet_partition(
                ctx["gw_targets"][i], warm_parts[i], warm_sched,
                args.batch_n, warm_out[i],
            )
        expect = {
            f"c{i}": len(warm_parts[i]) for i in range(fd.N_PARTITIONS)
        }
        warm_ok = _await_fleet_drained(
            ctx["consumers"], expect, timeout_s=240.0
        )
        print(f"capacity: warm-up drained={warm_ok}")

        # -- closed-loop calibration burst: the ladder needs a scale -----
        n_cal = 512
        cal_parts = synth_requests(n_cal, oid_base, fd)
        oid_base += n_cal
        cal_sched = OpenLoopSchedule(1e9, t0=time.perf_counter())
        cal_out: dict[int, dict] = {i: {} for i in range(fd.N_PARTITIONS)}
        t0 = time.perf_counter()
        cal_threads = [
            threading.Thread(
                target=_drive_fleet_partition,
                args=(ctx["gw_targets"][i], cal_parts[i], cal_sched,
                      args.batch_n, cal_out[i]),
            )
            for i in range(fd.N_PARTITIONS)
        ]
        for t in cal_threads:
            t.start()
        for t in cal_threads:
            t.join()
        expect = {
            f"c{i}": expect[f"c{i}"] + len(cal_parts[i])
            for i in range(fd.N_PARTITIONS)
        }
        _await_fleet_drained(ctx["consumers"], expect, timeout_s=240.0)
        cal_rate = n_cal / (time.perf_counter() - t0)
        print(f"capacity: calibration {cal_rate:.0f} orders/s closed-loop")

        rates = (
            [float(r) for r in args.rates.split(",")] if args.rates
            else geometric_ladder(
                0.30 * cal_rate, 1.60 * cal_rate, args.points
            )
        )
        ladder = []
        for r in rates:
            point, oid_base = run_fleet_point(
                ctx, rate=r, window_s=args.window, batch_n=args.batch_n,
                oid_base=oid_base,
            )
            ladder.append(point)
            print(
                f"capacity: offered {r:7.1f}/s delivered "
                f"{point['delivered_per_sec']:7.1f}/s "
                f"corrected p99 {point['corrected']['p99_s'] * 1e3:.0f}ms"
            )
            time.sleep(0.5)  # settle between points
    finally:
        for name, w in workers.items():
            w.stop()
        if resp is not None:
            resp.kill()
            resp.wait(timeout=10)

    config = {
        "partitions": fd.N_PARTITIONS,
        "symbols": N_SYMBOLS_FLEET,
        "batch_n": args.batch_n,
        "window_s": args.window,
        "points": len(rates),
        "calibration_orders_per_sec": round(cal_rate, 1),
        "delivered_floor": args.delivered_floor,
        "p99_budget_s": args.p99_budget_s,
        "histogram": HIST_KW,
        "engine": {
            "n_slots": fd.N_LANES, "max_t": fd.T_BINS,
            "cap": 64, "max_fills": 8, "dtype": "int64",
        },
        "drive": (
            "per-partition columnar DoOrderBatch over gRPC, routed by "
            "fleet.partition_of; gateways run with the tracer armed so "
            "admission takes the per-order scalar path (same workers as "
            "FLEET_r01)"
        ),
        "completion_source": (
            "gome_orders_consumed_total polled at 25 ms, inverted via "
            "per-partition FIFO with linear interpolation"
        ),
        "arrival_model": (
            "open-loop fixed schedule: intended_i = t0 + (i+1)/rate; "
            "latency charged from intended time"
        ),
    }
    extra = {
        "merge_lossless_all_points": all(
            p.get("merge_lossless") for p in ladder
        ),
    }
    return build_verdict(
        "fleet", config, ladder, args.delivered_floor, args.p99_budget_s,
        extra_checks=extra,
    )


# ===========================================================================


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fleet", action="store_true",
                    help="sweep the real 2x2 subprocess fleet "
                         "(default: in-process single service)")
    ap.add_argument("--seconds", type=float, default=10.0,
                    help="single mode: total sweep budget (window = "
                         "budget / points)")
    ap.add_argument("--window", type=float, default=4.0,
                    help="fleet mode: offered window per ladder point (s)")
    ap.add_argument("--points", type=int, default=6,
                    help="ladder points (>= 5 for the committed verdict)")
    ap.add_argument("--rates", default="",
                    help="comma list of offered rates (orders/s); "
                         "default: geometric 0.3x..1.6x of calibration")
    ap.add_argument("--batch-n", type=int, default=0,
                    help="orders per DoOrderBatch / frame (default: 256 "
                         "single, 32 fleet — small fleet batches keep "
                         "accumulation delay from burying the curve)")
    ap.add_argument("--symbols", type=int, default=32,
                    help="single mode: engine symbol slots")
    ap.add_argument("--cap", type=int, default=128,
                    help="single mode: book cap")
    ap.add_argument("--pipeline", type=int, default=2,
                    help="single mode: consumer pipeline depth")
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--delivered-floor", type=float, default=0.98,
                    help="knee rule: delivered/offered below this is "
                         "saturation")
    ap.add_argument("--p99-budget-s", type=float, default=1.0,
                    help="knee rule: corrected p99 above this is "
                         "saturation")
    ap.add_argument("--workdir", default="",
                    help="fleet mode scratch dir (default: tempdir)")
    ap.add_argument("--out", default="",
                    help="verdict JSON path (default: CAPACITY_r01.json "
                         "for --fleet, capacity_smoke.json otherwise)")
    args = ap.parse_args(argv)
    out = args.out or (
        "CAPACITY_r01.json" if args.fleet else "capacity_smoke.json"
    )
    if not args.batch_n:
        args.batch_n = 32 if args.fleet else 256

    if args.fleet:
        verdict = run_fleet_sweep(args)
    else:
        rates = (
            [float(r) for r in args.rates.split(",")] if args.rates else None
        )
        verdict = run_single_sweep(
            seconds=args.seconds, points=args.points,
            symbols=args.symbols, cap=args.cap, batch_n=args.batch_n,
            pipeline=args.pipeline, seed=args.seed,
            delivered_floor=args.delivered_floor,
            p99_budget_s=args.p99_budget_s, rates=rates,
        )
    write_json(out, verdict)
    print_verdict(verdict, out)
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

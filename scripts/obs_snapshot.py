"""Observability snapshot: run a small traced order drill through the
in-process service stack and dump the two operator surfaces to files —

  <out_dir>/metrics.txt   the /metrics Prometheus exposition (per-stage
                          gome_stage_seconds histograms included)
  <out_dir>/trace.json    one flight-recorder dump as Chrome trace-event
                          JSON (load in chrome://tracing or Perfetto)

    python scripts/obs_snapshot.py [out_dir=obs-artifacts]

CI (tier1.yml) uploads both as build artifacts after the test run, so
every push records what the pipeline's observability surfaces actually
look like — and a broken exposition/dump fails the step loudly.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(out_dir: str = "obs-artifacts") -> int:
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.service.app import EngineService
    from gome_tpu.utils.metrics import REGISTRY
    from gome_tpu.utils.trace import TRACER

    os.makedirs(out_dir, exist_ok=True)
    cfg = Config(
        engine=EngineConfig(cap=32, n_slots=16, max_t=8, dtype="int32"),
        # ops.enabled arms the order-lifecycle tracer (app wiring); the
        # HTTP server itself is not started — we snapshot in-process.
        ops=OpsConfig(enabled=True, trace=True, trace_keep=32),
    )
    svc = EngineService(cfg)
    # A handful of crossing + cancelled orders so every surface has data:
    # fills, a cancel notice, and complete ingress->publish journeys.
    for i in range(8):
        side = pb.SALE if i % 2 == 0 else pb.BUY
        r = svc.gateway.DoOrder(
            pb.OrderRequest(
                uuid=f"u{i}", oid=f"o{i}", symbol="eth2usdt",
                transaction=side, price=1.0, volume=2.0,
            ),
            None,
        )
        assert r.code == 0, r
    svc.gateway.DeleteOrder(
        pb.OrderRequest(
            uuid="u6", oid="o6", symbol="eth2usdt",
            transaction=pb.SALE, price=1.0, volume=2.0,
        ),
        None,
    )
    svc.pump()

    metrics = REGISTRY.render()
    assert "gome_stage_seconds" in metrics, "stage histograms missing"
    with open(os.path.join(out_dir, "metrics.txt"), "w") as f:
        f.write(metrics)

    dump = TRACER.recorder.chrome_trace()
    assert dump["traceEvents"], "flight recorder captured no journeys"
    with open(os.path.join(out_dir, "trace.json"), "w") as f:
        json.dump(dump, f, indent=1)

    journeys = {
        ev["args"]["trace_id"]
        for ev in dump["traceEvents"]
        if ev.get("ph") == "X"
    }
    print(
        f"wrote {out_dir}/metrics.txt ({len(metrics)} bytes) and "
        f"{out_dir}/trace.json ({len(dump['traceEvents'])} events, "
        f"{len(journeys)} journeys)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "obs-artifacts"))

"""Observability snapshot: run a small traced order drill through the
in-process service stack and dump the operator surfaces to files —

  <out_dir>/metrics.txt   the /metrics Prometheus exposition (per-stage
                          gome_stage_seconds histograms + the
                          gome_compile_seconds family included)
  <out_dir>/trace.json    one flight-recorder dump as Chrome trace-event
                          JSON (load in chrome://tracing or Perfetto)
  <out_dir>/cost.json     the /cost payload: compile journal (fed by a
                          frame drill through the fast path), live-buffer
                          residency, and the XLA cost model incl. the
                          donation-effectiveness report
  <out_dir>/timeline.json the /timeline payload: host-side sampler
                          series (RSS, rusage deltas, live buffers,
                          compile totals, geometry hash) — sampled
                          around the drill
  <out_dir>/profile.json  the /profile payload: the MEASURED roofline
                          (per-entry device time, achieved GFLOP/s,
                          efficiency vs the analytic ceiling) from a
                          bounded jax.profiler capture
  <out_dir>/perfetto_trace.json.gz  the capture's raw Perfetto artifact
  <out_dir>/hostprof.json the /hostprof payload: the host-CPU sampling
                          profiler's admit-drill report (per-stage
                          gateway ns/order, achievable orders/sec/core)
                          plus the live wall-profile join, PLUS the
                          columnar admit drill (round 11) under
                          "columnar_drill" — asserted at capture time
                          to >= 90% stage coverage with the taxonomy
                          summing back to the measured window
  <out_dir>/HOSTPROF_r02.json  copy of the committed columnar admit
                          roofline so the CI artifact bundle carries it
  <out_dir>/hostprof_collapsed.txt  the collapsed-stack (flamegraph
                          text) dump behind /hostprof?format=collapsed
  <out_dir>/fleet.json    the /fleet payload: the fleet aggregator's
                          merged view (per-member health + rollup,
                          counter-summed / proc-labeled merged
                          exposition, fleet-wide seq audit) over a
                          scripted two-member view of this process
  <out_dir>/capacity.json the /capacity payload (round 13): a real
                          ~10-second single-process smoke sweep of
                          scripts/capacity.py's open-loop ladder —
                          corrected percentiles, knee detection, and
                          the bottleneck-attribution table, asserted
                          at capture time to have non-empty rows that
                          sum back to e2e latency at every point
  <out_dir>/placement.json  the /placement payload (ISSUE 20): the
                          heavy-hitter symbol-flow sketch fed by the
                          drill's own admits, the occupancy ledger from
                          its dispatches, and the skew attribution —
                          asserted at capture time to have a non-empty
                          top table and a reconciled attribution
  <out_dir>/PLACEMENT_r01.json  copy of the committed what-if placement
                          verdict so the CI artifact bundle carries it

    python scripts/obs_snapshot.py [out_dir=obs-artifacts]

CI (tier1.yml) uploads all of these as build artifacts after the test run,
so every push records what the pipeline's observability surfaces actually
look like — and a broken exposition/dump fails the step loudly.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _order_frame(n: int, symbols: list, seed: int):
    """One deterministic ORDER-frame column dict (the fast-path shape)
    so the compile journal sees real first-seen dispatch combos."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return dict(
        n=n,
        action=np.ones(n, np.int64),
        side=rng.integers(0, 2, n).astype(np.int64),
        kind=np.zeros(n, np.int64),
        price=rng.integers(99_000, 101_000, n).astype(np.int64),
        volume=rng.integers(1, 10, n).astype(np.int64),
        symbols=symbols,
        symbol_idx=rng.integers(0, len(symbols), n).astype(np.int64),
        uuids=["u0"],
        uuid_idx=np.zeros(n, np.int64),
        oids=np.char.add("f", np.arange(n).astype("U6")).astype("S"),
    )


def main(out_dir: str = "obs-artifacts") -> int:
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.obs.compile_journal import JOURNAL
    from gome_tpu.obs.timeline import TIMELINE
    from gome_tpu.service.app import EngineService
    from gome_tpu.service.ops import OpsServer
    from gome_tpu.utils.metrics import REGISTRY
    from gome_tpu.utils.trace import TRACER

    os.makedirs(out_dir, exist_ok=True)
    # The drill's order.log is an artifact, not litter: route the file
    # handler into the output dir (utils.logging honors GOME_LOG_DIR)
    # instead of the CWD the reference default would hit.
    os.environ.setdefault("GOME_LOG_DIR", out_dir)
    cfg = Config(
        engine=EngineConfig(cap=32, n_slots=16, max_t=8, dtype="int32"),
        # ops.enabled arms the order-lifecycle tracer AND the compile
        # journal (app wiring); the HTTP server itself is not started —
        # we snapshot in-process.
        ops=OpsConfig(enabled=True, trace=True, trace_keep=32),
    )
    svc = EngineService(cfg)
    # ops.timeline armed the sampler at boot; the periodic thread only
    # runs while the service is start()ed, so the drill samples manually
    # (one baseline now, one after the traffic below).
    TIMELINE.sample()
    # A handful of crossing + cancelled orders so every surface has data:
    # fills, a cancel notice, and complete ingress->publish journeys.
    for i in range(8):
        side = pb.SALE if i % 2 == 0 else pb.BUY
        r = svc.gateway.DoOrder(
            pb.OrderRequest(
                uuid=f"u{i}", oid=f"o{i}", symbol="eth2usdt",
                transaction=side, price=1.0, volume=2.0,
            ),
            None,
        )
        assert r.code == 0, r
    svc.gateway.DeleteOrder(
        pb.OrderRequest(
            uuid="u6", oid="o6", symbol="eth2usdt",
            transaction=pb.SALE, price=1.0, volume=2.0,
        ),
        None,
    )
    svc.pump()
    # One ORDER frame through the engine fast path (below admission —
    # the drill's synthetic ADDs carry no pre-pool marks): the compile
    # journal hooks the frame dispatch's _seen_combos miss path, so this
    # is what puts real first-seen combos (and gome_compile_seconds
    # samples) in the snapshot.
    from gome_tpu.engine import frames

    symbols = [f"sym{i}" for i in range(4)]
    frames.apply_frame_fast(
        svc.engine.batch, _order_frame(64, symbols, seed=5)
    )

    metrics = REGISTRY.render()
    assert "gome_stage_seconds" in metrics, "stage histograms missing"
    assert "gome_compile_seconds" in metrics, "compile histograms missing"
    with open(os.path.join(out_dir, "metrics.txt"), "w") as f:
        f.write(metrics)

    dump = TRACER.recorder.chrome_trace()
    assert dump["traceEvents"], "flight recorder captured no journeys"
    with open(os.path.join(out_dir, "trace.json"), "w") as f:
        json.dump(dump, f, indent=1)

    # The /cost and /timeline payloads via the SAME code paths the HTTP
    # endpoint serves (OpsServer.cost_payload/timeline_payload), without
    # binding a socket.
    ops = OpsServer(svc)
    cost = ops.cost_payload()
    assert cost["compile_journal"]["entries"], "compile journal is empty"
    assert cost["cost_model"].get("entries"), "cost model empty"
    assert cost["live_buffers"]["total"]["count"] > 0, "no live buffers?"
    with open(os.path.join(out_dir, "cost.json"), "w") as f:
        json.dump(cost, f, indent=1, default=str)

    # The journal alone, in its GL906 wire form: CI (and operators
    # triaging a soak) feed this straight to
    # `scripts/gomelint.py --journal compile_journal.json` to prove the
    # observed dispatch combos never escaped the committed universe.
    journal_doc = JOURNAL.export()
    with open(os.path.join(out_dir, "compile_journal.json"), "w") as f:
        json.dump(journal_doc, f, indent=1, default=str)
    from gome_tpu.analysis.surface import journal_escapes, load_universe

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    universe = load_universe(
        os.path.join(root, "gome_tpu", "analysis", "combo_universe.json")
    )
    assert universe is not None, "no committed combo universe"
    escapes = journal_escapes(journal_doc["entries"], universe)
    assert not escapes, f"combos escaped the static universe: {escapes}"

    TIMELINE.sample()  # post-drill sample: the series shows the drill
    timeline = ops.timeline_payload()
    assert timeline["enabled"], "ops.timeline did not arm the sampler"
    assert len(timeline["samples"]) >= 2, "timeline captured no series"
    assert timeline["samples"][-1]["engine"]["geometry_hash"], timeline
    with open(os.path.join(out_dir, "timeline.json"), "w") as f:
        json.dump(timeline, f, indent=1, default=str)
    assert "gome_timeline_rss_bytes" in metrics, "timeline gauges missing"

    # The /profile payload (ops.profile armed the PROFILER at boot):
    # a bounded measured-roofline capture over the canonical entries,
    # plus the Perfetto artifact copied next to the JSON so CI's
    # observability-snapshot upload carries the raw trace too.
    import shutil

    from gome_tpu.obs.profiler import PROFILER

    profile = ops.profile_payload()
    assert profile["enabled"], "ops.profile did not arm the profiler"
    rep = profile["report"]
    assert rep and rep["entries"], "profile report is empty"
    measured = [
        r for r in rep["entries"].values()
        if "error" not in r and r.get("device_us_per_call", 0) > 0
    ]
    assert measured, f"no measured entries in profile report: {rep}"
    with open(os.path.join(out_dir, "profile.json"), "w") as f:
        json.dump(profile, f, indent=1, default=str)
    perfetto_out = None
    if rep.get("perfetto_trace") and os.path.exists(rep["perfetto_trace"]):
        perfetto_out = os.path.join(out_dir, "perfetto_trace.json.gz")
        shutil.copyfile(rep["perfetto_trace"], perfetto_out)

    # The /placement payload (ISSUE 20): ops.placement armed the
    # observatory at boot, so the scripted traffic above already fed
    # it — the 8 DoOrders + 1 Delete went through the gateway admit
    # hook into the symbol sketch, and pump()'s dense dispatch fed the
    # occupancy ledger. Captured HERE, before the hostprof admit drills
    # below push their own synthetic flow through the same gateway
    # hook and drown the scripted symbol. Assert the surface is real:
    # a non-empty heavy-hitter table topped by the drill's one symbol,
    # and an attribution whose components reconcile against the
    # observed rows-per-live-lane.
    from gome_tpu.obs.placement import PLACEMENT

    placement_doc = ops.placement_payload()
    assert placement_doc["enabled"], "ops.placement did not arm"
    pl_top = placement_doc["top"]
    assert pl_top and pl_top[0]["symbol"] == "eth2usdt", (
        f"heavy-hitter table missed the drill flow: {pl_top}"
    )
    pl_attr = placement_doc["attribution"]
    assert pl_attr["reconciliation"]["within_tol"], (
        f"placement attribution does not reconcile: {pl_attr}"
    )
    assert "gome_placement_admits_total" in REGISTRY.render(), (
        "placement gauges missing"
    )
    with open(os.path.join(out_dir, "placement.json"), "w") as f:
        json.dump(placement_doc, f, indent=1, default=str)
    # The committed what-if verdict rides along in the CI upload, same
    # as HOSTPROF_r02 below: every push's bundle carries the current
    # PLACEMENT_r01 policy table next to the live-measured sketch.
    r01 = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PLACEMENT_r01.json")
    if os.path.exists(r01):
        shutil.copyfile(r01, os.path.join(out_dir, "PLACEMENT_r01.json"))
    # The /hostprof payload (ops.hostprof armed HOSTPROF at boot): the
    # service is not start()ed here so the live wall sampler never ran —
    # the admit drill (run_drill, same as ?drill=1) supplies the
    # measured per-stage gateway breakdown, and the collapsed-stack
    # artifact rides next to the JSON.
    hostprof_doc = ops.hostprof_payload(run_drill=True)
    assert hostprof_doc["enabled"], "ops.hostprof did not arm HOSTPROF"
    drill = hostprof_doc["drill"]
    assert drill and drill["sampler"]["samples"] > 0, (
        f"hostprof drill captured no samples: {hostprof_doc}"
    )
    assert drill["stages"], "hostprof drill attributed no stages"
    # The COLUMNAR admit drill (round 11, the HOSTPROF_r02 flow at a
    # CI-sized order count) rides in the same artifact, with the
    # acceptance assertions enforced at capture time: the stage
    # taxonomy must attribute >= 90% of the sampled window, and the
    # per-stage ns/order must sum back to (>= 90% of) the measured
    # admit ns/order — a taxonomy hole or a stage-join bug fails the
    # snapshot step loudly instead of shipping a misleading artifact.
    from gome_tpu.obs import hostprof as hostprof_mod

    # The columnar dispatch rule sends traced RPCs down the scalar path
    # (per-order trace ids need per-order admits), and this process
    # armed the tracer at boot — park it for the drill so the measured
    # flow is the real array-native core, then restore it.
    _recorder = TRACER.recorder
    TRACER.disable()
    try:
        cdrill = hostprof_mod.gateway_drill(
            n_orders=16_384, seed=11, min_samples=32, max_rounds=8,
            path="columnar", batch_n=1024,
        )
    finally:
        TRACER.recorder = _recorder
    assert cdrill["coverage_pct"] >= 90.0, (
        f"columnar drill stage coverage {cdrill['coverage_pct']}% < 90%"
    )
    stage_sum = sum(
        row["ns_per_order"] for row in cdrill["stages"].values()
    )
    assert stage_sum >= 0.9 * cdrill["admit_ns_per_order"], (
        f"stage taxonomy sums to {stage_sum:.1f} ns/order, window is "
        f"{cdrill['admit_ns_per_order']} ns/order"
    )
    hostprof_doc["columnar_drill"] = cdrill
    with open(os.path.join(out_dir, "hostprof.json"), "w") as f:
        json.dump(hostprof_doc, f, indent=1, default=str)
    # The committed roofline artifact rides along in the CI upload so
    # every push's artifact bundle carries the current HOSTPROF_r02
    # verdict next to the freshly-measured drill above.
    r02 = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "HOSTPROF_r02.json")
    if os.path.exists(r02):
        import shutil as _shutil

        _shutil.copyfile(r02, os.path.join(out_dir, "HOSTPROF_r02.json"))
    from gome_tpu.obs.hostprof import HOSTPROF

    collapsed = HOSTPROF.collapsed()
    assert ";" in collapsed, f"no collapsed stacks: {collapsed[:200]}"
    with open(os.path.join(out_dir, "hostprof_collapsed.txt"), "w") as f:
        f.write(collapsed)

    # The capture (re)binds the per-entry gauges; re-render so
    # metrics.txt carries the gome_profile_* / gome_hostprof_* families.
    metrics = REGISTRY.render()
    assert "gome_profile_device_us" in metrics, "profile gauges missing"
    assert "gome_hostprof_" in metrics, "hostprof gauges missing"
    with open(os.path.join(out_dir, "metrics.txt"), "w") as f:
        f.write(metrics)

    # The /fleet payload (gome_tpu.obs.fleet): the aggregator federated
    # over a scripted two-member view of THIS process — fetch is
    # injected so no socket is bound; every surface is produced by the
    # same code path the HTTP endpoint serves. Two members sharing one
    # process exercises the real merge: counter totals double, gauges
    # fan out under proc labels, and the merged exposition must
    # re-render as a byte-identical (scrape-valid) document.
    from gome_tpu.obs.fleet import FLEET
    from gome_tpu.utils.metrics import parse_exposition, render_exposition

    surfaces = {
        "/metrics": lambda: REGISTRY.render(),
        "/healthz": lambda: json.dumps(
            ops.monitor.check().as_dict(), default=str
        ),
        "/durability": lambda: json.dumps(ops.durability_payload()),
        "/timeline": lambda: json.dumps(
            ops.timeline_payload(), default=str
        ),
        "/trace?format=journeys": lambda: json.dumps(
            TRACER.recorder.export()
        ),
    }

    def in_process_fetch(url: str, timeout_s: float) -> str:
        for suffix, fn in surfaces.items():
            if url.endswith(suffix):
                return fn()
        raise ValueError(f"unexpected fleet fetch: {url}")

    FLEET.install(
        {"alpha": "inproc://alpha", "beta": "inproc://beta"},
        fetch=in_process_fetch,
    )
    FLEET.poll()
    fleet_doc = FLEET.payload()
    assert fleet_doc["enabled"], "fleet aggregator did not arm"
    fleet_metrics = fleet_doc["metrics"]
    assert "error" not in fleet_metrics, fleet_metrics.get("error")
    merged_text = fleet_metrics["exposition"]
    assert render_exposition(parse_exposition(merged_text)) == merged_text, (
        "merged exposition does not re-render scrape-identically"
    )
    assert 'proc="alpha"' in merged_text, "gauge union lost the proc label"
    with open(os.path.join(out_dir, "fleet.json"), "w") as f:
        json.dump(fleet_doc, f, indent=1, default=str)
    FLEET.disable()

    # The /capacity payload (round 13): run the REAL smoke ladder —
    # scripts/capacity.py's open-loop single-process sweep, the same
    # entry point the CI capacity gate drives — and install the fresh
    # verdict into the CAPACITY singleton so the artifact is produced
    # by the same code path the HTTP endpoint serves. The sweep builds
    # its own engine/bus/consumer and arms a PRIVATE tracer (disabling
    # it on exit), so park this process's boot recorder around the call
    # exactly like the columnar drill above.
    import importlib.util

    from gome_tpu.obs.capacity import CAPACITY

    cap_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "capacity.py")
    spec = importlib.util.spec_from_file_location("_cap_sweep", cap_py)
    cap_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cap_mod)
    _recorder = TRACER.recorder
    TRACER.disable()
    try:
        cap_verdict = cap_mod.run_single_sweep(
            seconds=10.0, points=5, symbols=16, cap=64, batch_n=256,
        )
    finally:
        TRACER.recorder = _recorder
    assert len(cap_verdict["ladder"]) >= 5, cap_verdict["ladder"]
    for pt in cap_verdict["ladder"]:
        attr = pt["attribution"]
        assert attr["rows"], (
            f"empty attribution at {pt['offered_per_sec']}/s"
        )
        assert attr["within_tol"], (
            f"attribution misses e2e latency at {pt['offered_per_sec']}/s: "
            f"frac_err={attr['frac_err']}"
        )
    assert cap_verdict["checks"]["exactly_once_all_points"], cap_verdict
    CAPACITY.install(cap_verdict)
    capacity_doc = ops.capacity_payload()
    assert capacity_doc["enabled"], "capacity verdict did not arm"
    assert capacity_doc["verdict"]["schema"] == cap_verdict["schema"]
    cap_metrics = REGISTRY.render()
    assert "gome_capacity_points" in cap_metrics, "capacity gauges missing"
    with open(os.path.join(out_dir, "capacity.json"), "w") as f:
        json.dump(capacity_doc, f, indent=1, default=str)
    cap_knee = cap_verdict["knee"]
    CAPACITY.disable()

    journeys = {
        ev["args"]["trace_id"]
        for ev in dump["traceEvents"]
        if ev.get("ph") == "X"
    }
    n_compiles = len(cost["compile_journal"]["entries"])
    print(
        f"wrote {out_dir}/metrics.txt ({len(metrics)} bytes), "
        f"{out_dir}/trace.json ({len(dump['traceEvents'])} events, "
        f"{len(journeys)} journeys), {out_dir}/cost.json "
        f"({n_compiles} journaled compiles, "
        f"{len(cost['cost_model']['entries'])} cost-model entries), and "
        f"{out_dir}/timeline.json ({len(timeline['samples'])} samples), "
        f"{out_dir}/profile.json ({len(measured)} measured entries"
        + (f", perfetto at {perfetto_out}" if perfetto_out else "")
        + f"), {out_dir}/hostprof.json "
        f"({drill['sampler']['samples']} host samples, "
        f"{drill['admit_ns_per_order']} ns/order scalar admit, "
        f"{cdrill['admit_ns_per_order']} ns/order columnar admit at "
        f"{cdrill['coverage_pct']}% coverage), "
        f"{out_dir}/fleet.json ({len(fleet_doc['members'])} members, "
        f"{len(fleet_metrics['families'])} merged families), "
        f"{out_dir}/capacity.json ({capacity_doc['points']} ladder "
        f"points, knee "
        + (f"at {cap_knee['offered_per_sec']:.0f}/s offered"
           if cap_knee.get("found") else "not reached")
        + f", saturated stage: {cap_knee.get('saturated_stage')}), "
        f"{out_dir}/placement.json ({placement_doc['admits']} admits "
        f"sketched, top symbol {pl_top[0]['symbol']} at "
        f"{pl_top[0]['share']:.0%} share)"
    )
    JOURNAL.disable()
    TIMELINE.disable()
    PROFILER.disable()
    HOSTPROF.disable()
    PLACEMENT.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "obs-artifacts"))

#!/usr/bin/env python
"""What-if placement evaluator (ISSUE 20): score candidate symbol->shard
policies against the committed Zipf workload, host-side only.

ROADMAP items 1 and 2 share one disease — naive placement. The committed
measurements say what it costs today (``MULTICHIP_r06.json``: D=8 dense
shard skew 3.64, every shard padded to the hottest shard's row block;
``FLEET_r01.json``: 1.56x partition order imbalance) but nothing says
which policy would fix it. This evaluator replays the EXACT symbol-flow
profile MULTICHIP_r06 measured — ``np.random.default_rng(17)``,
``zipf(1.2, S // 4) % S`` over S=4096 symbols, the seeded Zipf/Hawkes
flow family of the deterministic simulator (gome_tpu.sim.flow,
arXiv:2510.08085; placement scoring consumes per-symbol arrival totals,
which the Zipf draw fixes) — against candidate placement policies and
predicts, per policy:

  * partition_imbalance_max_over_mean — per-partition order flow skew
    (the FLEET_r01 axis)
  * shard_skew — max per-shard live-lane count x D / live (the
    MULTICHIP_r06 axis)
  * rows_per_live_lane — the dense packer's real cost under its
    uniform-R_s pow2 row bucketing (engine.batch._grid_geometry)
  * padding_bytes_per_order — wasted op-grid bytes at the committed
    geometry (t=16, int32 cells)
  * symbols_moved_vs_current — migration cost vs today's layout

Everything is pure host-side arithmetic over the recorded flow — no
serving-path change, no device — and fully deterministic: running twice
produces byte-identical verdicts (tests/test_placement.py pins this and
the committed artifact). The verdict (schema
``gome-placement-verdict-v1``) carries the policy x metric table, the
skew-attribution rows reconciled against the committed observation, and
a named winner — the before/after contract ROADMAP item 2's fix must
honor.

Policies:

  current_block     today's engine layout: interner-ordered lanes in
                    contiguous per-shard blocks (lane // (S/D)) — must
                    reproduce MULTICHIP_r06's measured skew exactly,
                    which anchors the replay to the committed artifact.
  fnv1a_mod         the fleet's partition policy applied to lanes
                    (gome_tpu.fleet.router.partition_of — the one
                    blessed symbol hash tree-wide).
  consistent_hash   a 64-vnode-per-shard hash ring over the symbol
                    interner's names (fnv1a points, bisect lookup) —
                    minimal movement under resize, same long-run balance
                    class as fnv1a_mod.
  greedy_lpt        longest-processing-time flow balancing: symbols in
                    descending flow order, each to the least-flow-loaded
                    shard (ties: fewest lanes, lowest shard id). Needs
                    the measured flow profile — which is exactly what
                    the placement observatory's sketch records live.

Usage:
    python scripts/placement_eval.py                     # print verdict
    python scripts/placement_eval.py --out PLACEMENT_r01.json
"""

from __future__ import annotations

import argparse
import bisect
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gome_tpu.fleet.router import partition_of
from gome_tpu.obs.placement import SCHEMA, shard_skew_baseline
from gome_tpu.parallel.router import fnv1a

# The committed MULTICHIP_r06 workload + geometry, pinned (see
# scripts/mesh_overhead.py curve()): one fixed Zipf(1.2) live set over
# 4096 symbols, dispatched dense at D=8, t=16, int32 books.
SEED = 17
SYMBOLS = 4096
ZIPF_A = 1.2
DEVICES = 8
T = 16
CAP = 64
#: int32 op-grid cell: 3 x int32 index fields + 4 x int32 value fields
#: (obs.compile_journal.frame_combo_detail's ops_grid_bytes).
CELL_BYTES = 3 * 4 + 4 * 4
VNODES = 64
WINNER_SKEW_BUDGET = 1.3
RECONCILE_TOL = 0.05


def workload():
    """The committed flow profile: per-symbol arrival counts + live set.

    Identical draw to MULTICHIP_r06 (rng 17, zipf(1.2, S//4) % S), so
    the replay's ``current_block`` point must land on the committed
    measurement exactly."""
    rng = np.random.default_rng(SEED)
    draws = rng.zipf(ZIPF_A, size=SYMBOLS // 4) % SYMBOLS
    flow = np.bincount(draws, minlength=SYMBOLS)
    live = np.flatnonzero(flow)
    names = [f"SYM{i:04d}" for i in live]
    return flow[live].astype(np.int64), live.astype(np.int64), names


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


# -- candidate policies: live-symbol array -> shard id array --------------


def policy_current_block(live: np.ndarray, names, flow) -> np.ndarray:
    """Today's layout: interner order, contiguous per-shard lane blocks
    (engine.batch._grid_geometry: shard = lane // (n_slots / D))."""
    return live // (SYMBOLS // DEVICES)


def policy_fnv1a_mod(live, names, flow) -> np.ndarray:
    """The blessed fleet hash applied at lane granularity."""
    return np.array(
        [partition_of(n, DEVICES) for n in names], np.int64
    )


def policy_consistent_hash(live, names, flow) -> np.ndarray:
    """Hash ring over the symbol interner's names: VNODES points per
    shard, symbol owned by the first ring point at or clockwise of its
    own hash (wrapping). No modulo on the hash — ownership comes from
    the ring, so resizing moves only the symbols between new points."""
    points = sorted(
        (fnv1a(f"shard{p}/vnode{v}"), p)
        for p in range(DEVICES)
        for v in range(VNODES)
    )
    keys = [pt[0] for pt in points]
    owners = [pt[1] for pt in points]
    out = np.empty(len(names), np.int64)
    for i, n in enumerate(names):
        j = bisect.bisect_left(keys, fnv1a(n))
        out[i] = owners[j if j < len(owners) else 0]
    return out


def policy_greedy_lpt(live, names, flow) -> np.ndarray:
    """Greedy LPT flow balancing: heaviest symbol first, each to the
    shard with the least assigned flow (ties: fewest lanes, lowest
    id) — the classic 4/3-approximate makespan heuristic, applied to
    order flow. Deterministic: ties in flow break on symbol id."""
    order = sorted(range(len(live)), key=lambda i: (-int(flow[i]), int(live[i])))
    loads = [0] * DEVICES
    lanes = [0] * DEVICES
    out = np.empty(len(live), np.int64)
    for i in order:
        g = min(range(DEVICES), key=lambda d: (loads[d], lanes[d], d))
        out[i] = g
        loads[g] += int(flow[i])
        lanes[g] += 1
    return out


POLICIES = (
    ("current_block", policy_current_block),
    ("fnv1a_mod", policy_fnv1a_mod),
    ("consistent_hash", policy_consistent_hash),
    ("greedy_lpt", policy_greedy_lpt),
)


def score(groups: np.ndarray, flow: np.ndarray,
          current: np.ndarray) -> dict:
    """Predicted cost of one placement under the engine's real dense
    geometry: uniform per-shard row block R_s = pow2(max live count,
    min 8), every shard dispatching R_s rows (_grid_geometry)."""
    counts = np.bincount(groups, minlength=DEVICES)
    flows = np.bincount(groups, weights=flow, minlength=DEVICES)
    n_live = int(len(groups))
    orders = int(flow.sum())
    mx = int(counts.max())
    r_s = max(8, _next_pow2(mx))
    rows = r_s * DEVICES
    return {
        "partition_imbalance_max_over_mean": round(
            float(flows.max()) / (orders / DEVICES), 4
        ),
        "shard_skew": round(mx * DEVICES / n_live, 4),
        "r_s": r_s,
        "dispatched_rows": rows,
        "rows_per_live_lane": round(rows / n_live, 4),
        "padding_bytes_per_order": round(
            (rows - n_live) * T * CELL_BYTES / orders, 2
        ),
        "symbols_moved_vs_current": round(
            float((groups != current).mean()), 4
        ),
        "live_per_shard": [int(c) for c in counts],
    }


def build_verdict() -> dict:
    """The full deterministic verdict document (no clocks, no host
    state — same inputs, same bytes)."""
    flow, live, names = workload()
    orders = int(flow.sum())
    n_live = int(len(live))
    top16 = np.sort(flow)[::-1][:16]

    current = policy_current_block(live, names, flow)
    table = []
    for name, fn in POLICIES:
        row = {"policy": name}
        row.update(score(fn(live, names, flow), flow, current))
        table.append(row)

    # Attribution: decompose the CURRENT policy's predicted cost and
    # reconcile it against the committed MULTICHIP_r06 measurement —
    # the replay is only trustworthy if it reproduces the committed
    # observation it claims to explain.
    cur = table[0]
    skew = cur["shard_skew"]
    padding = cur["r_s"] / max(cur["live_per_shard"])
    product = skew * padding
    baseline = shard_skew_baseline() or {}
    observed = baseline.get("rows_per_live_lane") or cur["rows_per_live_lane"]
    frac = abs(product - observed) / observed
    skew_frac = (
        abs(skew - baseline["shard_skew"]) / baseline["shard_skew"]
        if baseline.get("shard_skew") else 0.0
    )
    attribution = {
        "observed": {
            "artifact": baseline.get("artifact"),
            "rows_per_live_lane": observed,
            "shard_skew": baseline.get("shard_skew"),
        },
        "components": [
            {"component": "lane_placement_skew", "value": round(skew, 4)},
            {"component": "cap_class_padding", "value": round(padding, 4)},
        ],
        "reconciliation": {
            "product": round(product, 4),
            "frac_err": round(frac, 6),
            "within_tol": frac <= RECONCILE_TOL,
            "replayed_skew_frac_err": round(skew_frac, 6),
            "tol": RECONCILE_TOL,
        },
    }

    winner = min(
        table,
        key=lambda r: (
            r["rows_per_live_lane"],
            r["partition_imbalance_max_over_mean"],
            r["policy"],
        ),
    )
    checks = {
        "attribution_reconciles": attribution["reconciliation"]["within_tol"],
        "replay_matches_committed_skew": skew_frac <= RECONCILE_TOL,
        "winner_shard_skew_le": WINNER_SKEW_BUDGET,
        "winner_within_budget": winner["shard_skew"] <= WINNER_SKEW_BUDGET,
    }
    checks["pass"] = all(
        v for k, v in checks.items() if isinstance(v, bool)
    )
    return {
        "schema": SCHEMA,
        "artifact": "PLACEMENT_r01",
        "method": (
            "host-side what-if replay of the committed MULTICHIP_r06 "
            f"Zipf({ZIPF_A}) flow (rng {SEED}, zipf(a, S//4) % S, "
            f"S={SYMBOLS}) against {len(POLICIES)} placement policies; "
            "each scored under the engine's real dense geometry "
            "(uniform R_s = pow2(max per-shard live), "
            "engine.batch._grid_geometry) at the committed t=16/int32 "
            "cell cost. Deterministic: no clocks, no device."
        ),
        "workload": {
            "seed": SEED,
            "symbols": SYMBOLS,
            "zipf_a": ZIPF_A,
            "orders": orders,
            "live_lanes": n_live,
            "devices": DEVICES,
            "t": T,
            "cap": CAP,
            "cell_bytes": CELL_BYTES,
            "top16_share": round(float(top16.sum()) / orders, 4),
        },
        "attribution": attribution,
        "policies": table,
        "winner": {
            "policy": winner["policy"],
            "predicted_shard_skew": winner["shard_skew"],
            "predicted_rows_per_live_lane": winner["rows_per_live_lane"],
            "rule": (
                "min rows_per_live_lane, then "
                "partition_imbalance_max_over_mean, then policy name"
            ),
        },
        "checks": checks,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None,
                    help="write the verdict JSON here (default: stdout)")
    args = ap.parse_args(argv)
    doc = build_verdict()
    text = json.dumps(doc, indent=1) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(
            f"wrote {args.out}: winner={doc['winner']['policy']} "
            f"(skew {doc['winner']['predicted_shard_skew']}), "
            f"pass={doc['checks']['pass']}"
        )
    else:
        print(text, end="")
    return 0 if doc["checks"]["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

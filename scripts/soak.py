"""Soak driver — the steady-state proof (ROADMAP open item 5, ISSUE 6).

Runs REAL gateway-step -> bus -> consumer -> engine traffic (the mixed
reference-driver-shaped flow from bench.py: Zipf symbols, ~45% cancels
incl. same-frame races, ~25% markets) on a WALL CLOCK for `--seconds`,
with the host-side timeline sampler (gome_tpu.obs.timeline) recording
RSS, getrusage deltas, live-buffer counts, compile totals, queue depth,
and the geometry-manifest hash throughout. The run ends in a VERDICT
block — pass/fail, machine-checkable, computed from the recorded series
and the obs leak detector:

  live_buffers_flat   obs.live.assert_steady_state on the post-soak
                      pipeline: N further frames leave the live device-
                      buffer count at its baseline (a growing count is a
                      leaked buffer);
  rss_bounded         host-memory growth over the steady window (first
                      40% of samples dropped as warm-in) is bounded:
                      least-squares slope under `--rss-slope-mb-per-min`,
                      OR absolute growth under `--rss-growth-mb` (short
                      runs: a slope over seconds is noise), OR growth
                      per PROCESSED ORDER under `--rss-bytes-per-order`.
                      The per-order bound is the contract the engine can
                      actually promise: the oid/uid interner tables are
                      grow-only BY DESIGN (every unique order id is
                      interned for cancel routing + event decode), so a
                      wall-clock soak's RSS slope is order-rate-
                      proportional — measured here at ~80 B/order on the
                      mixed flow — while a real leak (a retained device
                      buffer, an unbounded ring) blows through the
                      per-order budget as well;
  geometry_stable     the geometry-manifest hash holds still across the
                      last half of the run — a drifting hash means the
                      flow is still minting compiled shapes (~1s host
                      re-trace each), which a steady state cannot carry;
  zero_breaker_trips  no degraded-mode entries, no retryable rejects, no
                      spilled frames, no failed consumer steps.

`--latency-configs` then MEASURES the latency story (the "sub-100ms p50"
projection cited depth-1 / 16K-frame configurations no run had ever
executed — VERDICT r5): for each `<depth>x<frame>` config a fresh
closed-loop pipeline runs the mixed flow with the order-lifecycle tracer
armed, reporting end-to-end order->publish p50/p90/p99 AND the per-stage
breakdown (pad_pack / compile / device_execute / decode / publish) from
the PR 2 stage histograms. Every number in the payload is measured on
this host; `"measured": true` is asserted by tests/test_soak.py against
the committed SOAK_r01.json.

Usage:
    python scripts/soak.py --seconds 60 --out SOAK_r01.json
    python scripts/soak.py --seconds 10 --frame 512 --symbols 16  # smoke

Exit code 0 iff every verdict passed. CI (tier1.yml soak job) runs a
~60 s budget and uploads the SOAK + timeline artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Default to the CPU backend: the soak measures HOST steady state (RSS,
# allocations, shape churn) and must run in CI; SOAK_PLATFORM=tpu runs
# the same driver against the real chip.
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("SOAK_PLATFORM", "cpu"))

import numpy as np


def _parse_configs(spec: str) -> list[tuple[int, int]]:
    """"1x16384,2x16384" -> [(pipeline_depth, frame_orders), ...]."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        d, _, f = part.partition("x")
        out.append((int(d), int(f)))
    return out


def _counter_value(name: str) -> int:
    from gome_tpu.utils.metrics import REGISTRY

    return int(REGISTRY.counter(name).value())


_FAULT_COUNTERS = (
    "gome_gateway_retryable_rejects_total",
    "gome_gateway_spilled_frames_total",
    "gome_consumer_step_failures_total",
)


def _rss_fit(samples: list[dict]) -> dict:
    """Least-squares RSS slope (bytes/s), total growth, and growth per
    processed order over the given sample window (the samples' "orders"
    field is cumulative, so the window's order count is a diff)."""
    t = np.asarray([s["t"] for s in samples], np.float64)
    rss = np.asarray([s["rss_bytes"] for s in samples], np.float64)
    if len(t) >= 2 and t[-1] > t[0]:
        slope = float(np.polyfit(t - t[0], rss, 1)[0])
    else:
        slope = 0.0
    growth = int(rss[-1] - rss[0]) if len(rss) else 0
    orders = (
        int(samples[-1]["orders"] - samples[0]["orders"]) if samples else 0
    )
    return {
        "samples": len(samples),
        "window_s": round(float(t[-1] - t[0]), 3) if len(t) else 0.0,
        "slope_bytes_per_s": round(slope, 1),
        "slope_mb_per_min": round(slope * 60 / 2**20, 3),
        "growth_bytes": growth,
        "window_orders": orders,
        "growth_bytes_per_order": round(growth / max(orders, 1), 2),
        "first_bytes": int(rss[0]) if len(rss) else 0,
        "last_bytes": int(rss[-1]) if len(rss) else 0,
    }


def _build_stack(args, pipeline_depth: int, seed: int):
    """One gateway-step -> bus -> consumer -> engine pipeline plus its
    mixed-flow generator (fresh books; the caller warms it)."""
    import jax.numpy as jnp

    from bench import _MixedFlow
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    engine = MatchEngine(
        config=BookConfig(cap=args.cap, max_fills=16, dtype=jnp.int32),
        n_slots=args.symbols,
        max_t=32,
        kernel=args.kernel,
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=pipeline_depth,
    )
    flow = _MixedFlow(np.random.default_rng(seed), args.symbols)
    return engine, bus, consumer, flow


def run_soak(args) -> dict:
    """The wall-clock soak phase: warm the pipeline, arm the timeline,
    drive the mixed stream until the budget expires, then compute the
    verdict block from the recorded series."""
    import jax

    from bench import _svc_gateway_step, _svc_warmup
    from gome_tpu.obs import live
    from gome_tpu.obs.compile_journal import JOURNAL
    from gome_tpu.obs.timeline import TIMELINE, service_timeline
    from gome_tpu.utils.trace import TRACER

    engine, bus, consumer, flow = _build_stack(
        args, pipeline_depth=args.pipeline, seed=11
    )
    symbols = [f"sym{i}" for i in range(args.symbols)]
    make_frame = lambda: flow.frame(args.frame)

    # Warmup off the record: compiles, book fill-in, geometry margining.
    JOURNAL.install(keep_n=256)
    t0 = time.perf_counter()
    n_warm = _svc_warmup(engine, consumer, bus, make_frame, symbols)
    warm_s = time.perf_counter() - t0

    # Arm + start the sampler AFTER warmup: the rusage/RSS baseline and
    # every verdict window then describe the steady flow, not the
    # compile storm.
    TIMELINE.install(interval_s=args.interval, keep_n=args.timeline_keep)
    import types

    service_timeline(types.SimpleNamespace(engine=engine, bus=bus))
    faults0 = {name: _counter_value(name) for name in _FAULT_COUNTERS}
    TIMELINE.sample()
    TIMELINE.start()

    # The soak loop: closed-loop wall-clock traffic. One frame published
    # per iteration, one consumer step drained (with pipelining, frames
    # overlap exactly as in production), the match queue drained like a
    # real feed, and BOTH in-memory logs compacted past their committed
    # offsets — a wall-clock soak on an uncompacted in-process bus would
    # measure its own harness's retention, not the engine's steady
    # state. The deadline, not an order count, ends the run.
    from gome_tpu.bus.colwire import decode_event_frame

    deadline = time.monotonic() + args.seconds
    frames = orders = done = events = 0
    ev_off = bus.match_queue.end_offset()
    t0 = time.perf_counter()
    while time.monotonic() < deadline:
        cols = make_frame()
        _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
        frames += 1
        orders += int(cols["n"])
        done += consumer.run_once()
        for m in bus.match_queue.read_from(ev_off, 1 << 20):
            events += len(decode_event_frame(m.body))
            ev_off = m.offset + 1
        bus.match_queue.commit(ev_off)
        bus.match_queue.compact()
        bus.order_queue.compact()
    done += consumer.drain()
    for m in bus.match_queue.read_from(ev_off, 1 << 20):
        events += len(decode_event_frame(m.body))
        ev_off = m.offset + 1
    elapsed = time.perf_counter() - t0
    TIMELINE.stop()
    TIMELINE.sample()
    assert done == orders, (done, orders)

    series = TIMELINE.series()
    faults = {
        name: _counter_value(name) - faults0[name]
        for name in _FAULT_COUNTERS
    }

    # -- verdicts ----------------------------------------------------------
    verdicts: dict = {}

    def step():
        cols = make_frame()
        _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
        consumer.drain()

    try:
        leak = live.assert_steady_state(step, steps=6, settle=3)
        verdicts["live_buffers_flat"] = {
            "pass": True,
            "leaked": leak["leaked"],
            "baseline": leak["baseline"],
            "counts": leak["counts"],
        }
    except AssertionError as exc:
        verdicts["live_buffers_flat"] = {"pass": False, "detail": str(exc)}

    steady = series[max(len(series) * 2 // 5, 1):] or series
    fit = _rss_fit(steady)
    fit["pass"] = (
        fit["slope_mb_per_min"] <= args.rss_slope_mb_per_min
        or fit["growth_bytes"] <= args.rss_growth_mb * 2**20
        # The per-order budget: interner tables grow ~80 B per unique
        # order id by design (see module docstring); a leak grows faster.
        or fit["growth_bytes_per_order"] <= args.rss_bytes_per_order
    )
    verdicts["rss_bounded"] = fit

    tail = [
        s["engine"]["geometry_hash"]
        for s in series[len(series) // 2:]
        if isinstance(s.get("engine"), dict) and "geometry_hash" in s["engine"]
    ]
    verdicts["geometry_stable"] = {
        "pass": bool(tail) and len(set(tail)) == 1,
        "hashes": sorted(set(tail)),
        "window_samples": len(tail),
    }

    degraded = sum(
        1 for s in series
        if isinstance(s.get("batcher"), dict) and s["batcher"].get("degraded")
    )
    verdicts["zero_breaker_trips"] = {
        "pass": degraded == 0 and all(v == 0 for v in faults.values()),
        "degraded_samples": degraded,
        "fault_counter_deltas": faults,
    }

    verdicts["pass"] = all(
        v["pass"] for k, v in verdicts.items() if isinstance(v, dict)
    )
    st = engine.stats
    report = {
        "seconds_requested": args.seconds,
        "seconds_elapsed": round(elapsed, 3),
        "warmup_frames": n_warm,
        "warmup_s": round(warm_s, 3),
        "frames": frames,
        "orders": orders,
        "events": events,
        "throughput_orders_per_sec": round(orders / max(elapsed, 1e-9)),
        "engine": {
            "device_calls": st.device_calls,
            "cap_escalations": st.cap_escalations,
            "frame_fallbacks": st.frame_fallbacks,
            "cap": engine.config.cap,
        },
        "compile_journal": JOURNAL.summary(),
        "verdicts": verdicts,
        "timeline": series,
        "platform": jax.devices()[0].platform,
    }
    TIMELINE.disable()
    JOURNAL.disable()
    TRACER.disable()
    return report


def run_latency(args) -> dict:
    """The measured latency story: for each (depth, frame) config, a
    fresh closed-loop pipeline over the mixed flow with the order-
    lifecycle tracer armed — end-to-end order->publish percentiles plus
    the per-stage breakdown, every number measured on this host."""
    import jax

    from bench import _svc_gateway_step, _svc_warmup
    from gome_tpu.utils.metrics import Registry
    from gome_tpu.utils.trace import TRACER, FlightRecorder

    configs = []
    for depth, frame_n in _parse_configs(args.latency_configs):
        engine, bus, consumer, flow = _build_stack(
            args, pipeline_depth=depth, seed=11
        )
        symbols = [f"sym{i}" for i in range(args.symbols)]
        make_frame = lambda: flow.frame(frame_n)  # noqa: B023 — used eagerly
        _svc_warmup(engine, consumer, bus, make_frame, symbols)

        # Private registry per config: frame sizes must not pollute each
        # other's stage histograms.
        TRACER.install(FlightRecorder(keep_n=8), registry=Registry())
        n_frames = max(depth + 2, args.latency_orders // frame_n)
        frames = [make_frame() for _ in range(n_frames)]
        pub_t: list = []
        done_t: list = []
        t0 = time.perf_counter()
        for cols in frames:
            pub_t.append(time.perf_counter())
            _svc_gateway_step(
                cols, symbols, engine.pre_pool, bus.order_queue
            )
            n = consumer.run_once()
            now = time.perf_counter()
            for _ in range(n // frame_n):
                done_t.append(now)
        while len(done_t) < n_frames:
            n = consumer.run_once()
            now = time.perf_counter()
            for _ in range(n // frame_n):
                done_t.append(now)
        elapsed = time.perf_counter() - t0
        total = n_frames * frame_n
        rate = total / elapsed

        # Per-order latency: arrivals spread uniformly over each frame's
        # accumulation window at the sustained rate (bench --latency's
        # method — the batching bridge's wait is deliberately included).
        offs = (np.arange(frame_n, dtype=np.float64)[::-1] + 1) / rate
        lat = np.concatenate(
            [d - (p - offs) for p, d in zip(pub_t, done_t)]
        )
        p50, p90, p99 = np.percentile(lat, [50, 90, 99])

        # Corrected (intended-start) percentiles, ISSUE 17: the numbers
        # above anchor arrivals to each frame's ACTUAL publish, so a
        # pipeline stall slips the arrivals with it and queueing delay
        # escapes the percentiles (coordinated omission). The corrected
        # recorder charges every order from a FIXED open-loop schedule at
        # the sustained rate anchored at run start.
        from gome_tpu.obs.capacity import LogHistogram, OpenLoopSchedule

        sched = OpenLoopSchedule(rate, t0=t0)
        chist = LogHistogram(rel_err=0.01, min_value=1e-7, max_value=600.0)
        for f, d in enumerate(done_t):
            base = f * frame_n
            for v in (
                d - (t0 + (np.arange(frame_n) + base + 1) * sched.interval)
            ).tolist():
                chist.record(v if v > 0 else 0.0)
        cp50, cp90, cp99 = chist.percentiles((0.5, 0.9, 0.99))
        stages = {
            stage: {
                "count": v["count"],
                "mean_us": round(v["mean"] * 1e6, 1),
                "p50_us": round(v["p50"] * 1e6, 1),
                "p90_us": round(v["p90"] * 1e6, 1),
                "p99_us": round(v["p99"] * 1e6, 1),
            }
            for stage, v in sorted(
                TRACER.stage_percentiles((0.5, 0.9, 0.99)).items()
            )
        }
        TRACER.disable()
        configs.append({
            "label": f"depth{depth}_frame{frame_n}",
            "pipeline_depth": depth,
            "frame_orders": frame_n,
            "orders": total,
            "measured": True,
            "throughput_orders_per_sec": round(rate),
            "p50_ms": round(p50 * 1e3, 2),
            "p90_ms": round(p90 * 1e3, 2),
            "p99_ms": round(p99 * 1e3, 2),
            "closed_loop": {
                "p50_ms": round(p50 * 1e3, 2),
                "p90_ms": round(p90 * 1e3, 2),
                "p99_ms": round(p99 * 1e3, 2),
                "method": "arrivals anchored to actual publishes",
            },
            "corrected": {
                "p50_ms": round(cp50 * 1e3, 2),
                "p90_ms": round(cp90 * 1e3, 2),
                "p99_ms": round(cp99 * 1e3, 2),
                "method": (
                    "open-loop intended schedule at sustained rate "
                    "(coordinated-omission-safe)"
                ),
                "histogram_rel_err": 0.01,
            },
            "stages": stages,
        })
        print(
            f"# latency {configs[-1]['label']}: p50={p50 * 1e3:.1f}ms "
            f"p90={p90 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms at "
            f"{rate / 1e3:.0f}K orders/sec",
            file=sys.stderr,
        )
    return {
        "method": (
            "closed-loop mixed stream; per-order latency = frame "
            "resolve+publish time minus a synthetic arrival spread "
            "uniformly over the frame's accumulation window at the "
            "sustained rate; stages from the order-lifecycle tracer's "
            "histograms; each config also labels closed_loop vs "
            "corrected (intended-start, coordinated-omission-safe) "
            "percentile blocks"
        ),
        "platform": jax.devices()[0].platform,
        "configs": configs,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seconds", type=float, default=60.0,
                    help="soak wall-clock budget")
    ap.add_argument("--frame", type=int, default=4096,
                    help="orders per soak frame")
    ap.add_argument("--symbols", type=int, default=256)
    # The mixed flow's hot Zipf lane is depth-stationary around ~300
    # resting orders (bench._MixedFlow): cap 512 covers it WITHOUT a
    # mid-soak escalation, so the geometry-stability verdict measures
    # the flow, not a deliberately undersized book.
    ap.add_argument("--cap", type=int, default=512)
    ap.add_argument("--pipeline", type=int, default=2,
                    help="soak-phase cross-frame pipeline depth")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="timeline sampling period (s)")
    ap.add_argument("--timeline-keep", type=int, default=4096)
    ap.add_argument("--rss-slope-mb-per-min", type=float, default=8.0)
    ap.add_argument("--rss-growth-mb", type=float, default=8.0,
                    help="absolute steady-window RSS growth bound")
    ap.add_argument("--rss-bytes-per-order", type=float, default=256.0,
                    help="steady-window RSS growth budget per processed "
                         "order (covers the grow-only interner tables, "
                         "~80 B/order measured)")
    ap.add_argument("--latency-configs", default="1x16384,2x16384",
                    help='comma list of "<depth>x<frame>" latency runs')
    ap.add_argument("--latency-orders", type=int, default=65_536,
                    help="timed orders per latency config")
    ap.add_argument("--skip-latency", action="store_true")
    ap.add_argument("--out", default="SOAK_r01.json")
    ap.add_argument("--timeline-out", default=None,
                    help="separate timeline artifact (default: "
                         "<out stem>_timeline.json)")
    args = ap.parse_args(argv)

    import jax

    from bench import _enable_jax_cache

    _enable_jax_cache()
    args.kernel = "pallas" if jax.default_backend() == "tpu" else "scan"

    doc = {
        "meta": {
            "generated_unix": round(time.time(), 1),
            "argv": sys.argv[1:],
            "jax": jax.__version__,
            "platform": jax.default_backend(),
            "kernel": args.kernel,
            "frame": args.frame,
            "symbols": args.symbols,
            "cap": args.cap,
            "pipeline": args.pipeline,
        },
        "soak": run_soak(args),
    }
    if not args.skip_latency:
        doc["latency"] = run_latency(args)

    timeline_out = args.timeline_out or (
        os.path.splitext(args.out)[0] + "_timeline.json"
    )
    with open(timeline_out, "w") as f:
        json.dump({"samples": doc["soak"]["timeline"]}, f, indent=1)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, default=str)

    v = doc["soak"]["verdicts"]
    summary = {
        "metric": (
            f"soak {args.seconds:g}s mixed stream, {args.symbols} "
            f"symbols, {args.frame}-order frames, pipeline depth "
            f"{args.pipeline}, {args.kernel} kernel"
        ),
        "pass": v["pass"],
        "throughput_orders_per_sec":
            doc["soak"]["throughput_orders_per_sec"],
        "verdicts": {
            k: d["pass"] for k, d in v.items() if isinstance(d, dict)
        },
        "out": args.out,
    }
    print(json.dumps(summary))
    if not v["pass"]:
        print(f"# SOAK FAILED: {json.dumps(v, default=str)}",
              file=sys.stderr)
    return 0 if v["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

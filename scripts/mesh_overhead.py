"""Measure what the mesh costs (VERDICT r4 #5) — two parts:

Part A (runs wherever JAX runs; meaningful on the REAL chip): mesh=1
shard_map dispatch overhead. The same full grid at the headline service
shape (10240 lanes x 32 t, int32, pallas) steps through (a) the unsharded
engine path and (b) `sharded_batch_step` over a 1-device mesh — same
kernel, same bytes, the delta is what shard_map + sharding constraints
add per dispatch. Dense variant included (the Zipf hot path).

Part B (host-side analysis, no device needed): per-shard row-padding
overhead under Zipf skew. The dense packer buckets each shard's row block
to the MAX per-shard live count (engine/batch.py _grid_geometry), so skew
concentrates rows on one shard and every other shard pads to match. For
D in {1,2,4,8} over the service bench's own Zipf flow: dispatched-rows /
live-lanes ratio (p50/p95) — the true multi-chip tax of the dense win.

Part C (`--curve`): the MEASURED D=1/2/4/8 throughput + per-shard skew
curve (ISSUE 9 / ROADMAP open item 2), written to MULTICHIP_r06.json
with the measured-roofline profiler block embedded. Runs over 8 virtual
CPU devices on the dev container (curve shape + skew structure are
real; absolute rates are a CPU floor) and over real devices on a pod.

Usage:
    python scripts/mesh_overhead.py            # Part A on default backend
    python scripts/mesh_overhead.py --skew     # Part B (host only)
    python scripts/mesh_overhead.py --curve [out.json]   # Part C
Output: one JSON line per part (stored in ARCHITECTURE.md's table).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def part_a():
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine, BookConfig, init_books
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.parallel import make_mesh, shard_batch, sharded_batch_step
    from gome_tpu.parallel.mesh import sharded_dense_step

    S = int(os.environ.get("MESH_SYMBOLS", 10_240))
    T = int(os.environ.get("MESH_T", 32))
    CAP = int(os.environ.get("MESH_CAP", 256))
    REPS = int(os.environ.get("MESH_REPS", 100))
    config = BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32)

    rng = np.random.default_rng(3)
    n_ops = S * T

    def mk_grid(rows):
        f = {}
        shape = (rows, T)
        f["action"] = rng.integers(1, 2, shape)  # all ADDs
        f["side"] = rng.integers(0, 2, shape)
        f["is_market"] = np.zeros(shape, np.int64)
        f["price"] = rng.integers(90, 110, shape)
        f["volume"] = rng.integers(1, 50, shape)
        f["oid"] = np.arange(rows * T).reshape(shape) + 1
        f["uid"] = np.ones(shape, np.int64)
        from gome_tpu.engine.book import GRID_I32_FIELDS

        return DeviceOp(**{
            k: np.asarray(
                v, np.int32 if k in GRID_I32_FIELDS else config.dtype
            )
            for k, v in f.items()
        })

    ops = mk_grid(S)

    def sync(tree):
        """Force completion with a value fetch: block_until_ready on a
        sharded array over the tunneled backend returns before execution
        (observed: 30 chained full steps 'completing' in 2ms), so the
        probe syncs by materializing a scalar that depends on the
        result."""
        leaf = jax.tree.leaves(tree)[0]
        np.asarray(jax.device_get(leaf.sum()))

    def time_step(fn, books0, *args):
        """Thread the books output back in each iteration: steps must
        form a true serial chain (independent calls let the device/link
        pipeline them and the per-step time reads fictitiously low). The
        closing sync's own tunnel RTT (a flat ~0.1-1s on this link) is
        measured separately and subtracted so it does not smear a
        constant into every per-step time."""
        books, out = fn(books0, *args)  # compile
        sync(out)
        t0 = time.perf_counter()
        sync(books0)
        t_sync = time.perf_counter() - t0
        books = books0
        t0 = time.perf_counter()
        for _ in range(REPS):
            books, out = fn(books, *args)
        sync(books)
        return max(time.perf_counter() - t0 - t_sync, 1e-9) / REPS

    results = {}

    # Unsharded full-grid pallas step (the single-chip headline path).
    # device_put the grids up front for BOTH paths: numpy inputs would
    # re-upload ~10MB per call over the dev tunnel and measure the link.
    eng = BatchEngine(config, n_slots=S, max_t=T, kernel="pallas")
    ops = jax.device_put(ops)
    t_unsharded = time_step(lambda b, o: eng._step(b, o), eng.books, ops)
    results["full_unsharded_ms"] = round(t_unsharded * 1e3, 3)

    # mesh=1: the same step through shard_map + pinned shardings.
    mesh = make_mesh(1)
    stepper = sharded_batch_step(config, mesh, kernel="pallas")
    books_m = shard_batch(mesh, init_books(config, S))
    ops_m = shard_batch(mesh, ops)
    t_mesh1 = time_step(lambda b, o: stepper(b, o), books_m, ops_m)
    results["full_mesh1_ms"] = round(t_mesh1 * 1e3, 3)
    results["full_mesh1_overhead_pct"] = round(
        (t_mesh1 / t_unsharded - 1) * 100, 1
    )

    # Dense variant: 1024 live lanes of the 10240 (Zipf-ish live set).
    # Keep the host-born lane ids around as numpy: both the unsharded
    # device_put and the mesh placement shard from the host ORIGINAL —
    # round-tripping the device copy back through np.asarray would pay
    # device->host->device on the timed setup path (GL805).
    R = 1024
    dense_ops = jax.device_put(mk_grid(R))
    ids_np = np.arange(R, dtype=np.int32)
    lane_ids = jax.device_put(ids_np)
    eng2 = BatchEngine(config, n_slots=S, max_t=T, kernel="pallas")
    t_dense = time_step(
        lambda b, o: eng2._step(b, o, lane_ids), eng2.books, dense_ops
    )
    results["dense_unsharded_ms"] = round(t_dense * 1e3, 3)
    dstepper = sharded_dense_step(config, mesh, kernel="pallas")
    books2 = shard_batch(mesh, init_books(config, S))
    ids_m = shard_batch(mesh, ids_np)
    dops_m = shard_batch(mesh, dense_ops)
    t_dense_m = time_step(
        lambda b, i, o: dstepper(b, i, o), books2, ids_m, dops_m
    )
    results["dense_mesh1_ms"] = round(t_dense_m * 1e3, 3)
    results["dense_mesh1_overhead_pct"] = round(
        (t_dense_m / t_dense - 1) * 100, 1
    )
    results["orders_per_step"] = n_ops
    results["platform"] = jax.devices()[0].platform
    print(json.dumps({"mesh_overhead_mesh1": results}))


def part_b():
    """Row-padding overhead of per-shard max bucketing under Zipf skew —
    pure host analysis of the packer's own math (_grid_geometry)."""
    from gome_tpu.engine.batch import _next_pow2

    S = int(os.environ.get("MESH_SYMBOLS", 10_240))
    FRAMES = 64
    rng = np.random.default_rng(11)
    # The service bench's Zipf shape: symbol ~ Zipf(1.2) capped to S.
    rows = {}
    for d in (1, 2, 4, 8):
        ratios = []
        local = S // d
        for _ in range(FRAMES):
            syms = rng.zipf(1.2, size=8192) % S
            live = np.unique(syms)
            shard = live // local
            counts = np.bincount(shard, minlength=d)
            r_s = max(8, _next_pow2(int(counts.max())))
            dispatched = r_s * d
            ratios.append(dispatched / len(live))
        ratios = np.asarray(ratios)
        rows[f"D{d}"] = dict(
            p50_rows_per_live_lane=round(float(np.median(ratios)), 2),
            p95_rows_per_live_lane=round(
                float(np.percentile(ratios, 95)), 2
            ),
        )
    print(json.dumps({"mesh_dense_row_padding_zipf": rows}))


def _force_virtual_devices(n: int = 8) -> None:
    """Give this process `n` devices on the CPU backend (the conftest
    mechanism): the XLA flag and the platform must both land before
    jax's FIRST backend initialization — importing jax is fine, using a
    device is not. On a real pod slice set MESH_CURVE_PLATFORM= (empty)
    to keep the native device set instead."""
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    import jax

    platform = os.environ.get("MESH_CURVE_PLATFORM", "cpu")
    if platform:
        jax.config.update("jax_platforms", platform)
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            pass  # pre-0.5 JAX: the XLA_FLAGS spelling applies instead


def curve(out_path: str = "MULTICHIP_r06.json"):
    """The first MEASURED D=1/2/4/8 curve (ISSUE 9 / ROADMAP open item
    2): one fixed Zipf live set dispatched through the engine's real
    dense mesh path (`_grid_geometry` layout -> `sharded_dense_step`)
    at each mesh width, timing a serial dispatch chain AND replaying
    each shard's block independently on its own device
    (parallel.mesh.shard_execution_report) — so the JSON carries
    throughput, per-shard dispatched rows, per-shard live lanes, and
    per-shard execution time: the skew tax as measured numbers. The
    measured-roofline profiler block (gome_tpu.obs.profiler) is
    embedded alongside.

    On the dev/CI container the mesh is 8 VIRTUAL CPU devices sharing
    the host's cores: per-shard structure, skew ratios, and the curve's
    SHAPE are real measurements; absolute orders/sec are a CPU floor,
    not a chip claim. On a pod slice the same entry measures the real
    thing (MESH_CURVE_PLATFORM= to keep native devices)."""
    _force_virtual_devices(8)
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.engine.book import GRID_I32_FIELDS, DeviceOp
    from gome_tpu.obs import profiler
    from gome_tpu.parallel import make_mesh, shard_execution_report

    S = int(os.environ.get("MESH_CURVE_SYMBOLS", 4096))
    T = int(os.environ.get("MESH_CURVE_T", 16))
    CAP = int(os.environ.get("MESH_CURVE_CAP", 64))
    REPS = int(os.environ.get("MESH_CURVE_REPS", 20))
    config = BookConfig(cap=CAP, max_fills=8, dtype=jnp.int32)
    rng = np.random.default_rng(17)

    # ONE Zipf live set shared by every mesh width: the curve then
    # varies only in shard geometry, never in flow. S/4 draws keeps the
    # live set sparse enough that the dense packer engages at every D
    # (per-shard MAX bucketing must stay under the full grid) while the
    # hot-shard concentration still shows the real skew tax.
    live = np.unique(rng.zipf(1.2, size=S // 4) % S)

    def mk_grid(rows):
        shape = (rows, T)
        f = dict(
            action=np.ones(shape, np.int64),
            side=rng.integers(0, 2, shape),
            is_market=np.zeros(shape, np.int64),
            price=rng.integers(90, 110, shape),
            volume=rng.integers(1, 50, shape),
            oid=np.arange(rows * T).reshape(shape) + 1,
            uid=np.ones(shape, np.int64),
        )
        return DeviceOp(**{
            k: np.asarray(
                v, np.int32 if k in GRID_I32_FIELDS else config.dtype
            )
            for k, v in f.items()
        })

    points = []
    for d in (1, 2, 4, 8):
        mesh = make_mesh(d)
        eng = BatchEngine(config, n_slots=S, max_t=T, kernel="scan",
                          mesh=mesh)
        use_dense, n_rows, lane_ids, _ = eng._grid_geometry(live)
        assert use_dense, f"dense packer declined at D={d}"
        ops = mk_grid(n_rows)
        books, outs = eng._step(eng.books, ops, lane_ids)  # compile+warm
        jax.block_until_ready(outs)
        books = eng.books
        t0 = time.perf_counter()
        for _ in range(REPS):
            books, outs = eng._step(books, ops, lane_ids)
        jax.block_until_ready(books)
        per_step = (time.perf_counter() - t0) / REPS
        live_orders = len(live) * T
        shard_counts = np.bincount(live // (S // d), minlength=d)
        point = dict(
            devices=d,
            dispatched_rows=int(n_rows),
            live_lanes=int(len(live)),
            rows_per_live_lane=round(n_rows / len(live), 4),
            live_per_shard=[int(c) for c in shard_counts],
            shard_skew=round(int(shard_counts.max()) * d / len(live), 4),
            step_ms=round(per_step * 1e3, 3),
            live_orders_per_sec=round(live_orders / per_step),
            dispatched_orders_per_sec=round(n_rows * T / per_step),
        )
        if d > 1:
            point["per_shard"] = shard_execution_report(
                config, mesh, eng.books, lane_ids, ops
            )
        points.append(point)
        print(json.dumps({"multichip_point": point}), flush=True)

    doc = dict(
        artifact="MULTICHIP_r06",
        method=(
            "measured D=1/2/4/8 dense mesh dispatch over one fixed "
            "Zipf(1.2) live set; engine _grid_geometry layout through "
            "sharded_dense_step, serial chain best-effort mean of "
            f"{REPS} reps; per-shard blocks replayed independently per "
            "device (shard_execution_report). Virtual-CPU meshes share "
            "host cores: curve shape and skew are measurements, "
            "absolute rates are a CPU floor."
        ),
        platform=jax.devices()[0].platform,
        n_devices_available=jax.device_count(),
        jax=jax.__version__,
        geometry=dict(symbols=S, t=T, cap=CAP, reps=REPS),
        curve=points,
        profile=profiler.bench_measured("int32", repeats=4),
    )
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(f"wrote {out_path}")


if __name__ == "__main__":
    if "--skew" in sys.argv:
        part_b()
    elif "--curve" in sys.argv:
        curve(
            sys.argv[sys.argv.index("--curve") + 1]
            if len(sys.argv) > sys.argv.index("--curve") + 1
            and not sys.argv[sys.argv.index("--curve") + 1].startswith("-")
            else "MULTICHIP_r06.json"
        )
    else:
        part_a()

"""Full-output parity of the COMPILED Pallas kernel vs the scan path on a
real TPU (the pytest suite runs the kernel in interpreter mode on CPU; this
script closes the compiled-lowering gap). Run on a TPU host:

    python scripts/tpu_parity_check.py [S T CAP K G]

Exit 0 on exact equality of every book leaf and every StepOutput leaf
across chained grids of crossing flow (with cancels and market orders).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_parity(S=512, T=16, CAP=128, K=16, G=4, log=print) -> int:
    """Compiled-kernel vs scan parity on the current (TPU) backend.
    Returns 0 on exact equality of every leaf, 1 on mismatch, 2 on an
    unblockable S. Importable — bench.py gates every TPU pallas bench on
    this before reporting numbers."""
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.ops import pallas_available, pallas_batch_step

    if jax.default_backend() != "tpu":
        log("SKIP: no TPU backend (compiled-kernel parity needs one)")
        return 0
    assert pallas_available(jnp.int32)

    from gome_tpu.ops import default_block_s

    block_s = default_block_s(S)
    if block_s is None:
        log(f"S={S} has no valid compiled-kernel blocking "
            "(see gome_tpu.ops.default_block_s)")
        return 2
    config = BookConfig(cap=CAP, max_fills=K, dtype=jnp.int32)
    rng = np.random.default_rng(7)

    def grid(seed):
        r = np.random.default_rng(seed)
        action = r.choice([1, 1, 1, 2], size=(S, T)).astype(np.int32)
        return DeviceOp(
            action=action,
            side=r.integers(0, 2, (S, T)).astype(np.int32),
            is_market=(r.random((S, T)) < 0.1).astype(np.int32),
            price=r.integers(995_000, 1_005_000, (S, T)).astype(np.int32),
            volume=r.integers(1, 100, (S, T)).astype(np.int32),
            oid=(np.arange(S * T).reshape(S, T) % 97 + 1).astype(np.int32),
            uid=np.ones((S, T), np.int32),
        )

    b_scan = b_pall = init_books(config, S)
    for g in range(G):
        ops = grid(g)
        b_scan, o_scan = batch_step(config, b_scan, ops)
        b_pall, o_pall = pallas_batch_step(
            config, b_pall, ops, block_s=block_s, interpret=False
        )
        for name in o_scan._fields:
            a = np.asarray(jax.device_get(getattr(o_scan, name)))
            b = np.asarray(jax.device_get(getattr(o_pall, name)))
            if not np.array_equal(a, b):
                bad = np.argwhere(a != b)[:5]
                log(f"MISMATCH grid {g} StepOutput.{name} at {bad}")
                return 1
        for name in b_scan._fields:
            a = np.asarray(jax.device_get(getattr(b_scan, name)))
            b = np.asarray(jax.device_get(getattr(b_pall, name)))
            if not np.array_equal(a, b):
                bad = np.argwhere(a != b)[:5]
                log(f"MISMATCH grid {g} BookState.{name} at {bad}")
                return 1
        fills = int(np.asarray(jax.device_get(o_scan.n_fills)).sum())
        log(f"grid {g}: OK ({fills} fills)")
    log(f"PARITY OK: compiled pallas == scan on {G} grids "
        f"({S}x{T} ops each, cancels + markets included)")
    return 0


def main():
    args = [int(a) for a in sys.argv[1:6]]
    S, T, CAP, K, G = args + [512, 16, 128, 16, 4][len(args):]
    return run_parity(S, T, CAP, K, G)


if __name__ == "__main__":
    sys.exit(main())

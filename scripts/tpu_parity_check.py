"""Full-output parity of the COMPILED Pallas kernel vs the scan path on a
real TPU (the pytest suite runs the kernel in interpreter mode on CPU; this
script closes the compiled-lowering gap). Run on a TPU host:

    python scripts/tpu_parity_check.py [S T CAP K G]

Exit 0 on exact equality of every book leaf and every StepOutput leaf
across chained grids of crossing flow (with cancels and market orders).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_parity(S=512, T=16, CAP=128, K=16, G=4, log=print) -> int:
    """Compiled-kernel vs scan parity on the current (TPU) backend.
    Returns 0 on exact equality of every leaf, 1 on mismatch, 2 on an
    unblockable S. Importable — bench.py gates every TPU pallas bench on
    this before reporting numbers."""
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.ops import pallas_available, pallas_batch_step

    if jax.default_backend() != "tpu":
        log("SKIP: no TPU backend (compiled-kernel parity needs one)")
        return 0
    assert pallas_available(jnp.int32)

    from gome_tpu.ops import default_block_s

    block_s = default_block_s(S, CAP)
    if block_s is None:
        log(f"S={S} has no valid compiled-kernel blocking "
            "(see gome_tpu.ops.default_block_s)")
        return 2
    config = BookConfig(cap=CAP, max_fills=K, dtype=jnp.int32)
    rng = np.random.default_rng(7)

    def grid(seed):
        r = np.random.default_rng(seed)
        action = r.choice([1, 1, 1, 2], size=(S, T)).astype(np.int32)
        return DeviceOp(
            action=action,
            side=r.integers(0, 2, (S, T)).astype(np.int32),
            is_market=(r.random((S, T)) < 0.1).astype(np.int32),
            price=r.integers(995_000, 1_005_000, (S, T)).astype(np.int32),
            volume=r.integers(1, 100, (S, T)).astype(np.int32),
            oid=(np.arange(S * T).reshape(S, T) % 97 + 1).astype(np.int32),
            uid=np.ones((S, T), np.int32),
        )

    b_scan = b_pall = init_books(config, S)
    for g in range(G):
        ops = grid(g)
        b_scan, o_scan = batch_step(config, b_scan, ops)
        b_pall, o_pall = pallas_batch_step(
            config, b_pall, ops, block_s=block_s, interpret=False
        )
        if not _leaves_equal(o_scan, o_pall, f"grid {g} StepOutput", log):
            return 1
        if not _leaves_equal(b_scan, b_pall, f"grid {g} BookState", log):
            return 1
        fills = int(np.asarray(jax.device_get(o_scan.n_fills)).sum())
        log(f"grid {g}: OK ({fills} fills)")
    log(f"PARITY OK: compiled pallas == scan on {G} grids "
        f"({S}x{T} ops each, cancels + markets included)")
    return 0


def _leaves_equal(pair_a, pair_b, what, log) -> bool:
    import jax

    for name in pair_a._fields:
        a = np.asarray(jax.device_get(getattr(pair_a, name)))
        b = np.asarray(jax.device_get(getattr(pair_b, name)))
        if not np.array_equal(a, b):
            bad = np.argwhere(a != b)[:5]
            log(f"MISMATCH {what}.{name} at {bad}")
            return False
    return True


def run_dense_parity(R=8, T=128, CAP=32, K=8, S=64, log=print) -> int:
    """Compiled dense gather/scatter kernel (dense_kernel_step) vs the scan
    dense path on deep time axes — the time-blocked VMEM kernel's block_t
    loop is only exercised with T >> block_t."""
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, init_books
    from gome_tpu.engine.batch import dense_batch_step, dense_kernel_step
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.ops import default_block_s

    if jax.default_backend() != "tpu":
        log("SKIP dense: no TPU backend")
        return 0
    config = BookConfig(cap=CAP, max_fills=K, dtype=jnp.int32)
    bs = default_block_s(R, CAP)
    if bs is None:
        log(f"dense: R={R} unblockable")
        return 2
    r = np.random.default_rng(11)
    lane_ids = np.sort(r.choice(S, R, replace=False)).astype(np.int64)

    def ops(seed):
        q = np.random.default_rng(seed)
        return DeviceOp(
            action=q.choice([1, 1, 1, 2], size=(R, T)).astype(np.int32),
            side=q.integers(0, 2, (R, T)).astype(np.int32),
            is_market=(q.random((R, T)) < 0.1).astype(np.int32),
            price=q.integers(995_000, 1_005_000, (R, T)).astype(np.int32),
            volume=q.integers(1, 100, (R, T)).astype(np.int32),
            oid=(np.arange(R * T).reshape(R, T) % 211 + 1).astype(np.int32),
            uid=np.ones((R, T), np.int32),
        )

    b_scan = b_pall = init_books(config, S)
    ids = jnp.asarray(lane_ids)
    for g in range(2):
        o = ops(100 + g)
        b_scan, o_scan = dense_batch_step(config, b_scan, ids, o)
        b_pall, o_pall = dense_kernel_step(config, b_pall, ids, o, bs)
        if not _leaves_equal(o_scan, o_pall, f"dense grid {g} StepOutput", log):
            return 1
        if not _leaves_equal(b_scan, b_pall, f"dense grid {g} BookState", log):
            return 1
    log(f"dense PARITY OK: compiled dense kernel == scan dense path "
        f"({R}x{T} deep rounds, block_t covered)")
    return 0


def run_edge_price_parity(S=128, T=8, CAP=32, K=8, log=print) -> int:
    """Rebased int32 prices near the +/-2^30 envelope edges (what lane
    rebasing feeds the kernel for BTC-magnitude symbols)."""
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BookConfig, batch_step, init_books
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.ops import default_block_s, pallas_batch_step

    if jax.default_backend() != "tpu":
        log("SKIP edge: no TPU backend")
        return 0
    config = BookConfig(cap=CAP, max_fills=K, dtype=jnp.int32)
    bs = default_block_s(S, CAP)
    half = (1 << 30) - 1000

    def ops(seed, base):
        q = np.random.default_rng(seed)
        return DeviceOp(
            action=q.choice([1, 1, 1, 2], size=(S, T)).astype(np.int32),
            side=q.integers(0, 2, (S, T)).astype(np.int32),
            is_market=np.zeros((S, T), np.int32),
            price=(base + q.integers(-900, 900, (S, T))).astype(np.int32),
            volume=q.integers(1, 50, (S, T)).astype(np.int32),
            oid=(np.arange(S * T).reshape(S, T) % 97 + 1).astype(np.int32),
            uid=np.ones((S, T), np.int32),
        )

    b_scan = b_pall = init_books(config, S)
    for g, base in enumerate((half, -half)):
        o = ops(50 + g, base)
        b_scan, o_scan = batch_step(config, b_scan, o)
        b_pall, o_pall = pallas_batch_step(
            config, b_pall, o, block_s=bs, interpret=False
        )
        if not _leaves_equal(o_scan, o_pall, f"edge grid {g} StepOutput", log):
            return 1
        if not _leaves_equal(b_scan, b_pall, f"edge grid {g} BookState", log):
            return 1
    log("edge PARITY OK: rebased prices at +/-2^30 envelope edges")
    return 0


def run_engine_escalation_parity(log=print) -> int:
    """ENGINE-level differential on TPU with the compiled kernel: a
    sweep-heavy stream that trips cap + fill-record budgets, so the
    certified surface includes the escalation replay geometries
    (cap/max_fills doublings) and the frame fast path's rollback — not
    just the steady-state grid shape."""
    import jax

    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.oracle import OracleEngine
    from gome_tpu.types import Order, Side

    if jax.default_backend() != "tpu":
        log("SKIP escalation: no TPU backend")
        return 0
    import jax.numpy as jnp

    orders = [
        Order(uuid="u", oid=str(i), symbol=f"s{i % 3}", side=Side.SALE,
              price=100 + (i % 37), volume=1 + (i % 5))
        for i in range(120)
    ]
    orders.append(
        Order(uuid="u", oid="sweep", symbol="s0", side=Side.BUY, price=300,
              volume=10_000)  # >> max_fills resting orders: escalates
    )
    eng = BatchEngine(
        BookConfig(cap=8, max_fills=4, dtype=jnp.int32),
        n_slots=8, max_t=8, kernel="pallas",
    )
    got = []
    for i in range(0, len(orders), 40):
        got.extend(
            eng.process_columnar(orders[i : i + 40]).to_results()
        )
    oracle = OracleEngine()
    want = [r for o in orders for r in oracle.process(o)]
    if got != want:
        log(f"MISMATCH escalation stream: {len(got)} vs {len(want)} events")
        return 1
    if eng.stats.cap_escalations < 1:
        log("escalation: WARNING — stream did not escalate (geometry drift)")
    eng.verify_books()
    log(f"escalation PARITY OK: compiled kernel through cap/record "
        f"escalations == oracle ({len(got)} events, "
        f"{eng.stats.cap_escalations} escalations)")
    return 0


def run_fuzz_slice(cases=2, log=print) -> int:
    """A small compiled-mode slice of the differential fuzzer's geometry
    space (the three round-1 Mosaic crashes were all found by randomized
    geometries; CI only runs interpret mode)."""
    import jax

    if jax.default_backend() != "tpu":
        log("SKIP fuzz: no TPU backend")
        return 0
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.oracle import OracleEngine
    from gome_tpu.utils.streams import multi_symbol_stream

    rng = np.random.default_rng(int(os.environ.get("BENCH_FUZZ_SEED", "5")))
    for c in range(cases):
        cap = int(rng.choice([8, 16]))
        k = int(rng.choice([2, 4, 8]))
        n_sym = int(rng.integers(2, 6))
        orders = multi_symbol_stream(
            n=150, n_symbols=n_sym, seed=int(rng.integers(1, 1 << 30)),
            cancel_prob=0.2,
        )
        eng = BatchEngine(
            BookConfig(cap=cap, max_fills=k, dtype=jnp.int32),
            n_slots=8, max_t=8, kernel="pallas",
        )
        got = []
        for i in range(0, len(orders), 50):
            got.extend(
                eng.process_columnar(orders[i : i + 50]).to_results()
            )
        oracle = OracleEngine()
        want = [r for o in orders for r in oracle.process(o)]
        if got != want:
            log(f"MISMATCH fuzz case {c} (cap={cap} K={k} syms={n_sym})")
            return 1
        eng.verify_books()
        log(f"fuzz case {c} OK (cap={cap} K={k} syms={n_sym}, "
            f"{len(got)} events)")
    log(f"fuzz PARITY OK: {cases} compiled-mode randomized geometries")
    return 0


def run_suite(S=128, T=8, CAP=256, K=16, G=2, log=print) -> int:
    """The full certification the bench gates on: every code path _step can
    select on TPU — full grids (incl. cancels + markets), dense deep
    rounds (block_t), envelope-edge prices, escalation replays, and a
    compiled-mode fuzz slice."""
    for fn in (
        lambda: run_parity(S=S, T=T, CAP=CAP, K=K, G=G, log=log),
        lambda: run_dense_parity(log=log),
        lambda: run_edge_price_parity(CAP=min(CAP, 32), log=log),
        lambda: run_engine_escalation_parity(log=log),
        lambda: run_fuzz_slice(log=log),
    ):
        rc = fn()
        if rc == 1:
            return 1
    return 0


def main():
    args = [int(a) for a in sys.argv[1:6] if not a.startswith("--")]
    S, T, CAP, K, G = args + [512, 16, 128, 16, 4][len(args):]
    if "--suite" in sys.argv or not args:
        return run_suite(S=128, T=8, CAP=CAP, K=K, G=G)
    return run_parity(S, T, CAP, K, G)


if __name__ == "__main__":
    sys.exit(main())

"""Race drill — the gomerace dynamic prong run against REAL service flow.

Boots a full EngineService with ``GOME_RACECHECK=1`` (the app-level hook
arms analysis.racecheck's Eraser-style lockset detector over the
matchfeed, its SeqTracker, the consumer's seq frontier, and the batcher
when present), then drives concurrent gateway→bus→consumer→matchfeed
traffic the way production sees it:

  * N gateway threads submitting mixed add/cancel flow through the real
    ``DoOrder``/``DeleteOrder`` handlers (no gRPC socket — the handlers
    ARE the concurrency surface; the wire adds nothing to lock
    discipline),
  * the consumer and matchfeed daemon loops running live,
  * one subscriber draining the fan-out stream (the SubscribeMatches
    path's queue handoff).

The run ends in a machine-checkable JSON verdict: orders accepted,
events fanned out, and every lockset violation the detector recorded —
both stacks, deduped by fingerprint. Exit 0 iff traffic actually flowed
AND no unsuppressed race was reported; a suppression (see
``RaceCheck.suppress``) must cite a documented benign-race
justification. CI (tier1.yml ``race`` job) runs this after the GL7xx
static sweep: the static pass proves the *contracts* are declared, the
drill proves the code *honors* them under real interleavings.

Usage:
    GOME_RACECHECK=1 python scripts/race_drill.py --seconds 6
    python scripts/race_drill.py --seconds 3 --threads 2   # smoke
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The drill IS the racecheck mode; set it before EngineService is built
# so the app-level hook arms the detector.
os.environ["GOME_RACECHECK"] = "1"

SYMBOL = "eth2usdt"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seconds", type=float, default=6.0,
                    help="wall-clock traffic window")
    ap.add_argument("--threads", type=int, default=4,
                    help="concurrent gateway submitter threads")
    ap.add_argument("--out", default="",
                    help="write the JSON verdict here too")
    args = ap.parse_args(argv)

    from gome_tpu.analysis.racecheck import RACECHECK
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.config import Config
    from gome_tpu.service.app import EngineService

    svc = EngineService(Config())
    assert RACECHECK.enabled, "GOME_RACECHECK hook did not arm"
    # Tens of thousands of per-fill INFO lines would bury the verdict.
    import logging

    logging.getLogger("gome_tpu.matchfeed").setLevel(logging.WARNING)
    svc.consumer.start()
    svc.feed.start()

    stop = threading.Event()
    accepted = [0] * args.threads
    rejected = [0] * args.threads
    sub_events = [0]

    def gateway_worker(i: int) -> None:
        rng = random.Random(0xACE + i)
        n = 0
        resting: list[str] = []
        while not stop.is_set():
            n += 1
            oid = f"o{i}-{n}"
            if resting and rng.random() < 0.3:
                # cancel flow rides the same handlers/batcher path
                dead = resting.pop(rng.randrange(len(resting)))
                svc.gateway.DeleteOrder(
                    pb.OrderRequest(
                        uuid=f"u{i}", oid=dead, symbol=SYMBOL,
                        transaction=pb.BUY, price=1.0, volume=1.0,
                    ),
                    None,
                )
                continue
            side = pb.BUY if rng.random() < 0.5 else pb.SALE
            r = svc.gateway.DoOrder(
                pb.OrderRequest(
                    uuid=f"u{i}", oid=oid, symbol=SYMBOL,
                    transaction=side,
                    price=round(rng.uniform(0.90, 1.10), 2),
                    volume=float(rng.randint(1, 5)),
                ),
                None,
            )
            if r.code == 0:
                accepted[i] += 1
                resting.append(oid)
            else:
                rejected[i] += 1

    def subscriber() -> None:
        # Real fan-out consumer: the generator's queue handoff is the
        # SubscribeMatches path; it ends when the feed stops.
        for _ in svc.feed.subscribe():
            sub_events[0] += 1

    sub = threading.Thread(target=subscriber, name="drill-subscriber")
    sub.start()
    workers = [
        threading.Thread(target=gateway_worker, args=(i,),
                         name=f"drill-gateway-{i}")
        for i in range(args.threads)
    ]
    t0 = time.monotonic()
    for w in workers:
        w.start()
    time.sleep(args.seconds)
    stop.set()
    for w in workers:
        w.join(timeout=30)
    # Let the consumer/feed drain the tail before stopping the loops.
    deadline = time.monotonic() + 10
    while (svc.bus.order_queue.committed() < svc.bus.order_queue.end_offset()
           and time.monotonic() < deadline):
        time.sleep(0.05)
    svc.consumer.stop()
    svc.feed.stop()
    sub.join(timeout=10)
    RACECHECK.disable()

    reports = RACECHECK.reports()
    all_reports = RACECHECK.reports(include_suppressed=True)
    verdict = {
        "seconds": round(time.monotonic() - t0, 2),
        "gateway_threads": args.threads,
        "orders_accepted": sum(accepted),
        "orders_rejected": sum(rejected),
        "events_fanned_out": svc.feed.events_seen,
        "subscriber_events": sub_events[0],
        "matchfeed_seq": svc.feed.seq.state(),
        "race_reports_total": len(all_reports),
        "race_reports_suppressed": len(all_reports) - len(reports),
        "race_reports": [r.format() for r in reports],
        "race_report_stacks": [
            {"here": list(r.site_here), "prev": list(r.site_prev)}
            for r in reports
        ],
    }
    verdict["passed"] = (
        verdict["orders_accepted"] > 0
        and verdict["events_fanned_out"] > 0
        and not reports
    )
    text = json.dumps(verdict, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0 if verdict["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Profile the consumer hot path CPU at the service-bench shape (dev tool).

Replicates bench.py service_main's setup, then cProfiles the timed
consumer drain so the per-stage CPU cost is visible without tunnel noise
(process_time is still reported; cProfile overhead inflates everything
uniformly)."""

import cProfile
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
from bench import (
    _enable_jax_cache,
    _svc_columns,
    _svc_gateway_step,
    _svc_warmup,
)

_enable_jax_cache()
if os.environ.get("PROF_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["PROF_PLATFORM"])

import jax.numpy as jnp

from gome_tpu.bus import MemoryQueue, QueueBus
from gome_tpu.engine import BookConfig
from gome_tpu.engine import frames as engine_frames
from gome_tpu.engine.orchestrator import MatchEngine
from gome_tpu.service.consumer import OrderConsumer

N = int(os.environ.get("SVC_ORDERS", 524_288))
FRAME = int(os.environ.get("SVC_FRAME", 262_144))
S = int(os.environ.get("SVC_SYMBOLS", 10_240))
CAP = int(os.environ.get("SVC_CAP", 256))
PIPE = int(os.environ.get("SVC_PIPELINE", 2))

engine = MatchEngine(
    config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
    n_slots=S, max_t=32, kernel="pallas",
    dense_t_max=int(os.environ.get("SVC_DENSE_T", 8192)),
)
# Load the service bench's persisted geometry manifest (same default
# path) so the profile sees the converged shapes, not trace/compile noise.
geom = os.environ.get(
    "SVC_GEOMETRY",
    os.path.join(
        os.environ.get("GOME_JAX_CACHE", "/root/.cache/gome_jax"),
        f"svc_geometry_S{S}_C{CAP}_F{FRAME}.json",
    ),
)
n_pre = engine.load_geometry(geom)
print(f"precompiled {n_pre} combos from {geom}", file=sys.stderr)
bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
consumer = OrderConsumer(
    engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
    pipeline_depth=PIPE,
)

rng = np.random.default_rng(7)
symbols = [f"sym{i}" for i in range(S)]
FRAME = min(FRAME, N)
# Same warm-until-stable + margin-pinning as bench.py service_main:
# profile only steady-state frames. PROF_MIXED=1 profiles the mixed
# (headline) stream instead of the clean one.
oid_box = [1]
if os.environ.get("PROF_MIXED"):
    flow = bench._MixedFlow(rng, S)
    make_frame = lambda: flow.frame(FRAME)
else:
    def make_frame():
        cols = _svc_columns(rng, FRAME, S, oid_box[0])
        oid_box[0] += FRAME
        return cols

n_warm = _svc_warmup(
    engine, consumer, bus, make_frame, symbols, margin=n_pre == 0
)
print(f"warm_frames={n_warm}", file=sys.stderr)

frames_cols = [make_frame() for _ in range(-(-N // FRAME))]
engine_frames.FETCH_SECONDS = 0.0

for cols in frames_cols:
    _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)

prof = cProfile.Profile()
t0 = time.perf_counter()
c0 = time.process_time()
prof.enable()
n_done = consumer.drain()
prof.disable()
cpu = time.process_time() - c0
wall = time.perf_counter() - t0
print(
    f"orders={n_done} wall={wall:.3f}s cpu={cpu:.3f}s "
    f"fetch={engine_frames.FETCH_SECONDS:.3f}s "
    f"-> {n_done / cpu / 1e6:.2f}M orders/sec/core ({cpu / n_done * 1e6:.3f} us/order)",
    file=sys.stderr,
)
st = pstats.Stats(prof, stream=sys.stderr)
st.sort_stats("cumulative").print_stats(30)
st.sort_stats("tottime").print_stats(30)

"""Profile the service's host CPU (dev tool, rebased onto obs.hostprof).

Two drills:

  consumer (default)   replicate bench.py service_main's setup, then
      profile the timed consumer drain. Sampling mode (obs.hostprof's
      in-process sampler — near-zero skew, per-stage ns/order + collapsed
      stacks) is the default; ``--deterministic`` keeps the old cProfile
      run (exact call counts, uniform ~2x inflation).

  --gateway            profile the admit loop specifically: the
      deterministic host-only gateway drill (no engine, no jax) under
      SIGPROF sampling — measured admit ns/order, achievable
      orders/sec/core, the function-by-function stage split, and the
      host-vs-device roofline. ``--out HOSTPROF_r01.json`` writes the
      committed artifact payload.

    python scripts/profile_consumer.py                     # sampled drain
    python scripts/profile_consumer.py --deterministic     # cProfile drain
    python scripts/profile_consumer.py --gateway           # admit drill
    python scripts/profile_consumer.py --gateway --out HOSTPROF_r01.json
    python scripts/profile_consumer.py --gateway --columnar \
        --out HOSTPROF_r02.json                            # columnar admit
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("SVC_ORDERS", 524_288))
FRAME = int(os.environ.get("SVC_FRAME", 262_144))
S = int(os.environ.get("SVC_SYMBOLS", 10_240))
CAP = int(os.environ.get("SVC_CAP", 256))
PIPE = int(os.environ.get("SVC_PIPELINE", 2))


def gateway_main(args) -> int:
    """The admit-loop drill: host-only (no jax import), deterministic
    request stream, SIGPROF sampling. Emits the HOSTPROF_r01 payload
    (scalar path) or, with --columnar, the HOSTPROF_r02 payload (the
    same seeded flow through the array-native batch admit core)."""
    from gome_tpu.obs import hostprof

    doc = hostprof.hostprof_artifact(
        n_orders=args.orders or 30_000,
        seed=args.seed,
        min_samples=args.min_samples,
        # Columnar rounds are ~100x shorter, so the sample budget needs
        # far more of them.
        max_rounds=48 if args.columnar else 8,
        artifact="HOSTPROF_r02" if args.columnar else "HOSTPROF_r01",
        path="columnar" if args.columnar else "scalar",
        batch_n=args.batch_n,
    )
    drill = doc["drill"]
    print(
        f"gateway admit: {drill['orders']} orders in {drill['wall_s']}s "
        f"-> {drill['admit_ns_per_order']} ns/order "
        f"({drill['admit_orders_per_sec_per_core']} orders/sec/core), "
        f"{drill['sampler']['samples']} samples "
        f"({drill['sampler']['mode']} mode), "
        f"coverage {drill['coverage_pct']}%",
        file=sys.stderr,
    )
    for st, row in drill["stages"].items():
        print(
            f"  {st:<14} {row['pct']:>6.2f}%  "
            f"{row['ns_per_order']:>9.1f} ns/order "
            f"({row['samples']} samples)",
            file=sys.stderr,
        )
    body = json.dumps(doc, indent=1, default=str)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(body + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(body)
    return 0


def _consumer_setup():
    """bench.py service_main's setup: pallas engine at the service
    geometry, persisted-manifest precompile, warm-until-stable frames."""
    import bench
    from bench import (
        _enable_jax_cache,
        _svc_columns,
        _svc_gateway_step,
        _svc_warmup,
    )

    _enable_jax_cache()
    if os.environ.get("PROF_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["PROF_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer

    engine = MatchEngine(
        config=BookConfig(cap=CAP, max_fills=16, dtype=jnp.int32),
        n_slots=S, max_t=32, kernel="pallas",
        dense_t_max=int(os.environ.get("SVC_DENSE_T", 8192)),
    )
    # Load the service bench's persisted geometry manifest (same default
    # path) so the profile sees converged shapes, not trace/compile noise.
    geom = os.environ.get(
        "SVC_GEOMETRY",
        os.path.join(
            os.environ.get("GOME_JAX_CACHE", "/root/.cache/gome_jax"),
            f"svc_geometry_S{S}_C{CAP}_F{FRAME}.json",
        ),
    )
    n_pre = engine.load_geometry(geom)
    print(f"precompiled {n_pre} combos from {geom}", file=sys.stderr)
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
        pipeline_depth=PIPE,
    )

    rng = np.random.default_rng(7)
    symbols = [f"sym{i}" for i in range(S)]
    frame_n = min(FRAME, N)
    # Same warm-until-stable + margin-pinning as bench.py service_main:
    # profile only steady-state frames. PROF_MIXED=1 profiles the mixed
    # (headline) stream instead of the clean one.
    oid_box = [1]
    if os.environ.get("PROF_MIXED"):
        flow = bench._MixedFlow(rng, S)
        make_frame = lambda: flow.frame(frame_n)
    else:
        def make_frame():
            cols = _svc_columns(rng, frame_n, S, oid_box[0])
            oid_box[0] += frame_n
            return cols

    n_warm = _svc_warmup(
        engine, consumer, bus, make_frame, symbols, margin=n_pre == 0
    )
    print(f"warm_frames={n_warm}", file=sys.stderr)

    frames_cols = [make_frame() for _ in range(-(-N // frame_n))]
    for cols in frames_cols:
        _svc_gateway_step(cols, symbols, engine.pre_pool, bus.order_queue)
    return consumer


def consumer_main(args) -> int:
    from gome_tpu.engine import frames as engine_frames
    from gome_tpu.obs import hostprof

    consumer = _consumer_setup()
    engine_frames.FETCH_SECONDS = 0.0

    prof = None
    sampler = None
    if args.deterministic:
        import cProfile

        prof = cProfile.Profile()
    else:
        sampler = hostprof.HostSampler(hz=args.hz)

    t0 = time.perf_counter()
    c0 = time.process_time()
    if prof is not None:
        prof.enable()
    else:
        sampler.start()
    n_done = consumer.drain()
    if prof is not None:
        prof.disable()
    else:
        sampler.stop()
    cpu = time.process_time() - c0
    wall = time.perf_counter() - t0
    print(
        f"orders={n_done} wall={wall:.3f}s cpu={cpu:.3f}s "
        f"fetch={engine_frames.FETCH_SECONDS:.3f}s "
        f"-> {n_done / cpu / 1e6:.2f}M orders/sec/core "
        f"({cpu / n_done * 1e6:.3f} us/order)",
        file=sys.stderr,
    )
    if prof is not None:
        import pstats

        st = pstats.Stats(prof, stream=sys.stderr)
        st.sort_stats("cumulative").print_stats(30)
        st.sort_stats("tottime").print_stats(30)
        return 0
    join = hostprof.stage_join(
        sampler.counts(), n_orders=n_done, window_ns=wall * 1e9
    )
    print(
        f"sampled {sampler.samples} stacks ({sampler.mode_used} mode, "
        f"{args.hz} Hz), stage coverage {join['coverage_pct']}%",
        file=sys.stderr,
    )
    for stage, row in join["stages"].items():
        print(
            f"  {stage:<14} {row['pct']:>6.2f}%  "
            f"{row.get('ns_per_order', 0):>9.1f} ns/order "
            f"({row['samples']} samples)",
            file=sys.stderr,
        )
    print("# top collapsed stacks:", file=sys.stderr)
    for line in sampler.collapsed(max_lines=20).splitlines():
        print(f"  {line}", file=sys.stderr)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(sampler.collapsed())
        print(f"wrote collapsed stacks -> {args.out}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="profile_consumer",
                                 description=__doc__)
    ap.add_argument("--gateway", action="store_true",
                    help="profile the gateway admit loop (host-only "
                         "drill) instead of the consumer drain")
    ap.add_argument("--columnar", action="store_true",
                    help="--gateway: drive the columnar batch admit "
                         "core (DoOrderBatch -> GCO4) and emit the "
                         "HOSTPROF_r02 payload")
    ap.add_argument("--batch-n", type=int, default=1024,
                    help="--gateway --columnar: orders per "
                         "OrderBatchRequest")
    ap.add_argument("--deterministic", action="store_true",
                    help="consumer drill: cProfile instead of sampling")
    ap.add_argument("--out", default="",
                    help="--gateway: write the HOSTPROF_r01 payload "
                         "here; consumer sampling: write collapsed "
                         "stacks here")
    ap.add_argument("--orders", type=int, default=0,
                    help="--gateway drill size (default 30000)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--min-samples", type=int, default=800,
                    help="--gateway: keep re-running rounds until the "
                         "sampler holds this many stacks")
    ap.add_argument("--hz", type=float, default=997.0,
                    help="sampler cadence")
    args = ap.parse_args(argv)
    if args.gateway:
        return gateway_main(args)
    return consumer_main(args)


if __name__ == "__main__":
    sys.exit(main())

"""Standalone marker-store bench: bounds the RESP server's throughput on
the sharded topology's admission path (VERDICT r3 weak #7 — the
thread-per-connection Python server sits on every shard's admission path;
nothing previously bounded it at production rates).

Measures, against a fresh respserver process over a real socket:
  * mark_frame-style marking: grouped variadic HSETs, one pipelined round
    trip per frame (the gateway side);
  * admission-style consumption: one pipelined round trip of per-key
    HDELs per frame (the consumer side).

Prints one JSON line per direction with orders/sec.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from gome_tpu.engine.prepool import RespPrePool
from gome_tpu.persist.resp import RespClient

N = int(os.environ.get("MARKER_ORDERS", 1 << 20))
FRAME = int(os.environ.get("MARKER_FRAME", 1 << 15))
N_SYMBOLS = int(os.environ.get("MARKER_SYMBOLS", 1024))


def main():
    srv = subprocess.Popen(
        [sys.executable, "-m", "gome_tpu.persist.respserver", "--port", "0"],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        ready = srv.stdout.readline().split()
        assert ready and ready[0] == "READY", ready
        port = int(ready[1])
        pool = RespPrePool(RespClient(port=port))

        rng = np.random.default_rng(5)
        symbols = [f"sym{i}" for i in range(N_SYMBOLS)]
        frames = []
        oid0 = 0
        for start in range(0, N, FRAME):
            n = min(FRAME, N - start)
            frames.append(
                dict(
                    n=n,
                    action=np.ones(n, np.uint8),
                    symbols=symbols,
                    symbol_idx=rng.integers(0, N_SYMBOLS, n).astype(
                        np.uint32
                    ),
                    uuids=["u"],
                    uuid_idx=np.zeros(n, np.uint32),
                    oids=np.char.add(
                        "o", np.arange(oid0, oid0 + n).astype("U12")
                    ).astype("S"),
                )
            )
            oid0 += n

        # Warmup (connection, server JIT-ish costs).
        pool.mark_frame(frames[0])
        t0 = time.perf_counter()
        for cols in frames[1:]:
            pool.mark_frame(cols)
        mark_s = time.perf_counter() - t0
        n_marked = sum(int(c["n"]) for c in frames[1:])

        def consume(cols):
            keys = [
                (symbols[k], "u", o.decode())
                for k, o in zip(
                    cols["symbol_idx"].tolist(), cols["oids"].tolist()
                )
            ]
            return pool.consume_batch(keys)

        consume(frames[0])
        t0 = time.perf_counter()
        hits = 0
        for cols in frames[1:]:
            hits += sum(consume(cols))
        del_s = time.perf_counter() - t0
        assert hits == n_marked, (hits, n_marked)

        print(
            json.dumps(
                {
                    "metric": (
                        f"marker-server mark_frame (grouped variadic HSET, "
                        f"{FRAME}-order frames, real RESP socket)"
                    ),
                    "value": round(n_marked / mark_s),
                    "unit": "orders/sec",
                }
            )
        )
        print(
            json.dumps(
                {
                    "metric": (
                        f"marker-server consume (pipelined HDEL, "
                        f"{FRAME}-order frames, real RESP socket)"
                    ),
                    "value": round(n_marked / del_s),
                    "unit": "orders/sec",
                }
            )
        )
    finally:
        srv.terminate()
        srv.wait(timeout=10)


if __name__ == "__main__":
    main()

"""Differential fuzz: device engine vs the pure-Python oracle on randomized
streams under adversarial engine geometries (tiny caps -> constant cap
escalation, tiny max_fills -> record escalations, max_t=1 -> per-op grids,
lane growth, int32 rebasing at extreme price bases, and all three decode
paths: object, columnar, and ORDER frames through MatchEngine admission +
the cross-frame device pipeline).

    python scripts/fuzz.py [n_cases] [seed0] [--tpu]

Prints one line per case; exits nonzero on the first divergence with a
reproducer description. Runs on CPU by default — the fuzz target is
SEMANTICS, and every randomized geometry is a fresh ~30s TPU compile over
the tunnel; pass --tpu to fuzz the real-TPU lowering anyway.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def configure(tpu: bool = False) -> None:
    """Set the jax global config the fuzz cases need. Called from main() —
    NOT at import time, so importing this module (the CI slice in
    tests/test_fuzz.py does) never mutates process-global jax state; the
    test harness's conftest owns that configuration there.

    Enable x64 up front in BOTH modes: int64 cases would flip it mid-process
    (engine.book.ensure_dtype_usable), and flipping jax_enable_x64 between
    traced cases can send jax's dtype-promotion cache into infinite
    recursion on a later pallas retrace (observed on TPU). Caveat: int32
    SCAN-path cases therefore fuzz under x64-on promotion, whereas the
    production bench runs x64 off — the compiled-kernel trace is x64-immune
    (pallas_match pins the flag off), and bench.py itself covers the x64-off
    scan configuration."""
    import jax

    if not tpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def run_case(seed: int) -> str:
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.oracle import OracleEngine
    from gome_tpu.types import Action, Order, OrderType, Side

    rng = np.random.default_rng(seed)
    cap = int(rng.choice([4, 8, 16, 64]))
    max_fills = int(rng.choice([1, 2, 4, 8]))
    max_t = int(rng.choice([1, 3, 16]))
    n_slots = int(rng.choice([1, 2, 8, 16]))
    dtype = jnp.int32 if rng.random() < 0.5 else jnp.int64
    # object: per-order path; columnar: vectorized decode; frame: ORDER
    # frames through MatchEngine admission + the cross-frame device
    # pipeline (random depth) — the native host ops' differential target.
    mode = str(rng.choice(["object", "columnar", "frame"]))
    n_symbols = int(rng.choice([1, 3, 7]))
    base_price = int(
        rng.choice(
            [100, 10_000_000,
             10_000_000_000_000 if dtype == jnp.int32 else 100_000]
        )
    )
    band = int(rng.choice([3, 50, 5_000]))
    n_orders = int(rng.choice([50, 200]))
    market_p = float(rng.choice([0.0, 0.15]))
    cancel_p = float(rng.choice([0.0, 0.3]))
    chunk = int(rng.choice([1, 17, 64]))

    orders = []
    # (symbol, oid, side, price) of prior limit ADDs: cancels need the exact
    # resting side+price to hit (SURVEY §2.3.2); most cancels target those,
    # a minority deliberately miss (wrong price) to cover the not-found path.
    live: list[tuple[str, str, Side, int]] = []
    for i in range(n_orders):
        sym = f"s{int(rng.integers(n_symbols))}"
        if live and rng.random() < cancel_p:
            sym_o, oid, side_o, price_o = live[int(rng.integers(len(live)))]
            if rng.random() < 0.25:  # deliberate miss
                price_o = price_o + int(rng.integers(1, band + 2))
            orders.append(
                Order(uuid="u", oid=oid, symbol=sym_o, side=side_o,
                      price=price_o, volume=0, action=Action.DEL)
            )
            continue
        kind = OrderType.MARKET if rng.random() < market_p else OrderType.LIMIT
        side = Side(int(rng.integers(2)))
        price = (
            0 if (kind is OrderType.MARKET and rng.random() < 0.5)
            else base_price + int(rng.integers(-band, band + 1))
        )
        orders.append(
            Order(uuid=f"u{int(rng.integers(3))}", oid=str(i), symbol=sym,
                  side=side, price=price, volume=int(rng.integers(1, 30)),
                  order_type=kind)
        )
        if kind is OrderType.LIMIT:
            live.append((sym, str(i), side, price))

    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    # GOME_FUZZ_KERNEL=pallas (with --tpu) fuzzes the COMPILED kernel inside
    # the full engine: escalation replays, rebasing, growth — each geometry
    # is a fresh Mosaic compile, so keep case counts small on TPU. The
    # engine falls back to scan when the compiled kernel cannot run (int64,
    # unblockable lane counts); the effective path is printed per case so a
    # green run cannot masquerade as compiled-kernel coverage.
    kernel = os.environ.get("GOME_FUZZ_KERNEL", "scan")
    if kernel not in ("scan", "pallas"):
        raise ValueError(f"GOME_FUZZ_KERNEL must be scan|pallas, got {kernel!r}")
    depth = 0
    if mode == "frame":
        from gome_tpu.bus.colwire import decode_order_frame, encode_orders
        from gome_tpu.engine.orchestrator import MatchEngine
        from gome_tpu.engine.pipeline import FramePipeline

        depth = int(rng.choice([1, 2, 3]))
        meng = MatchEngine(
            config=BookConfig(cap=cap, max_fills=max_fills, dtype=dtype),
            n_slots=n_slots, max_t=max_t, kernel=kernel,
        )
        engine = meng.batch
        for o in orders:
            meng.mark(o)
        pipe = FramePipeline(meng, depth=depth)
        got = []
        for i in range(0, len(orders), chunk):
            cols = decode_order_frame(encode_orders(orders[i : i + chunk]))
            for _tok, batch in pipe.feed(cols):
                got.extend(batch.to_results())
        for _tok, batch in pipe.flush():
            got.extend(batch.to_results())
    else:
        engine = BatchEngine(
            BookConfig(cap=cap, max_fills=max_fills, dtype=dtype),
            n_slots=n_slots, max_t=max_t, kernel=kernel,
        )
        got = []
        for i in range(0, len(orders), chunk):
            part = orders[i : i + chunk]
            if mode == "columnar":
                got.extend(engine.process_columnar(part).to_results())
            else:
                got.extend(engine.process(part))
    from gome_tpu.ops import default_block_s, pallas_available

    effective = (
        "pallas"
        if kernel == "pallas"
        and pallas_available(dtype)
        and default_block_s(engine.n_slots) is not None
        else "scan"
    )
    desc = (
        f"seed={seed} cap={cap} K={max_fills} max_t={max_t} slots={n_slots} "
        f"dtype={np.dtype(dtype).name} mode={mode}"
        f"{f'(depth={depth})' if depth else ''} "
        f"kernel={effective} base={base_price} band={band} n={n_orders} "
        f"chunk={chunk}"
    )
    if got != expected:
        first = next(
            (j for j, (a, b) in enumerate(zip(got, expected)) if a != b),
            min(len(got), len(expected)),
        )
        raise AssertionError(
            f"DIVERGENCE [{desc}] events {len(got)} vs {len(expected)}, "
            f"first mismatch at {first}:\n got: "
            f"{got[first] if first < len(got) else '<none>'}\n exp: "
            f"{expected[first] if first < len(expected) else '<none>'}"
        )
    engine.verify_books()
    return (
        f"OK [{desc}] events={len(got)} esc="
        f"{engine.stats.cap_escalations}"
        f"/{engine.stats.fill_record_escalations}"
    )


def run_sim_case(seed: int) -> str:
    """Oracle parity on SIM-generated flow (gome_tpu.sim): a seeded
    Hawkes/Zipf stream — clustered arrivals, Zipf-hot lanes, book-coupled
    placement, and cancels targeting really-resting (oid, price) pairs —
    exercises resting-queue depths and cancel patterns the uniform
    stream above never reaches. The grid is linearized in (t, lane)
    order (per-lane order preserved; lanes are independent) and fed to
    both the oracle and a randomized adversarial engine geometry."""
    import jax
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.oracle import OracleEngine
    from gome_tpu.sim.env import EnvConfig, env_reset
    from gome_tpu.sim.flow import FlowConfig
    from gome_tpu.sim.replay import _record_step, orders_from_grid

    rng = np.random.default_rng(seed)
    flow = FlowConfig(
        n_lanes=int(rng.choice([2, 4, 7])),
        t_bins=int(rng.choice([32, 64])),
        # Hotter-than-default excitation drives deeper bursts.
        excite_self=float(rng.choice([0.25, 0.45])),
        cancel_rate=float(rng.choice([0.8, 1.4, 2.0])),
        market_rate=float(rng.choice([0.2, 0.8])),
        offset_p=float(rng.choice([0.2, 0.5])),
        vol_max=int(rng.choice([5, 60])),
    )
    # Generation-side geometry is generous (cap 64) so the stream's
    # cancel targets come from a faithfully evolved book; the engine
    # under test gets an ADVERSARIAL geometry below.
    gen_cfg = EnvConfig(
        flow=flow, book=BookConfig(cap=64, max_fills=8, dtype=jnp.int32)
    )
    n_grids = int(rng.choice([8, 20]))
    state, _ = env_reset(gen_cfg, jax.random.PRNGKey(seed))
    orders = []
    for _ in range(n_grids):
        state, bg_ops, _info = _record_step(gen_cfg, state)
        orders.extend(orders_from_grid(jax.device_get(bg_ops)._asdict()))

    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    cap = int(rng.choice([4, 8, 16]))
    max_fills = int(rng.choice([1, 2, 4]))
    max_t = int(rng.choice([1, 3, 16]))
    n_slots = int(rng.choice([1, 2, flow.n_lanes]))
    dtype = jnp.int32 if rng.random() < 0.5 else jnp.int64
    mode = str(rng.choice(["object", "columnar"]))
    chunk = int(rng.choice([1, 17, 64]))
    engine = BatchEngine(
        BookConfig(cap=cap, max_fills=max_fills, dtype=dtype),
        n_slots=n_slots, max_t=max_t,
    )
    got = []
    for i in range(0, len(orders), chunk):
        part = orders[i : i + chunk]
        if mode == "columnar":
            got.extend(engine.process_columnar(part).to_results())
        else:
            got.extend(engine.process(part))
    desc = (
        f"seed={seed} SIM lanes={flow.n_lanes} t_bins={flow.t_bins} "
        f"grids={n_grids} n={len(orders)} cap={cap} K={max_fills} "
        f"max_t={max_t} slots={n_slots} dtype={np.dtype(dtype).name} "
        f"mode={mode} chunk={chunk}"
    )
    if got != expected:
        first = next(
            (j for j, (a, b) in enumerate(zip(got, expected)) if a != b),
            min(len(got), len(expected)),
        )
        raise AssertionError(
            f"DIVERGENCE [{desc}] events {len(got)} vs {len(expected)}, "
            f"first mismatch at {first}:\n got: "
            f"{got[first] if first < len(got) else '<none>'}\n exp: "
            f"{expected[first] if first < len(expected) else '<none>'}"
        )
    engine.verify_books()
    return (
        f"OK [{desc}] events={len(got)} esc="
        f"{engine.stats.cap_escalations}"
        f"/{engine.stats.fill_record_escalations}"
    )


def main():
    configure(tpu="--tpu" in sys.argv)
    sim = "--sim" in sys.argv
    args = [a for a in sys.argv[1:] if a not in ("--tpu", "--sim")]
    n = int(args[0]) if len(args) > 0 else 30
    seed0 = int(args[1]) if len(args) > 1 else 1000
    case = run_sim_case if sim else run_case
    for s in range(seed0, seed0 + n):
        print(case(s), flush=True)
    print(f"ALL {n} CASES PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())

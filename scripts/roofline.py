"""Device-kernel roofline probe (ARCHITECTURE.md's roofline section).

Two parts:

  * the ANALYTIC per-entry roofline table — arithmetic intensity
    (flops / bytes accessed) straight from the compiled executables via
    gome_tpu.obs.costmodel, replacing the hand-derived estimates this
    script used to carry. Printed first on every run; `--table` prints
    it alone (works on any backend, CPU included).
  * the MEASURED roofline (`--measured`): a jax.profiler capture over
    the same canonical entries, joined against the analytic table —
    per-entry device time, achieved GFLOP/s / GB/s, and efficiency vs
    the machine ceiling (gome_tpu.obs.profiler; any backend). `--table`
    stays the analytic-only fallback.
  * the MEASURED sweep: times the compiled Pallas match kernel at the
    headline shape while sweeping the knobs that distinguish the
    candidate ceilings:

      - cap sweep    — per-step work is O(cap) vector ops over
                       [block_s, cap] tiles; if throughput scales ~1/cap
                       the kernel is compute/dependency-bound, not
                       launch-bound;
      - block_t sweep — deeper time blocks amortize grid/launch overhead;
                       a plateau means launches are not the ceiling;
      - block_s sweep — more lanes per block raises SIMD utilization.

    Prints one JSON line per point: {cap, block_t, block_s,
    orders_per_sec, cycles_per_block_step} (cycles = block_s * f /
    throughput, f = 940 MHz for v5e — the serial per-step critical path
    the dependency chain pays).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _enable_jax_cache, build_grids

_enable_jax_cache()

import jax
import jax.numpy as jnp
import numpy as np

from gome_tpu.engine import BookConfig, init_books
from gome_tpu.engine.book import DeviceOp
from gome_tpu.ops import pallas_batch_step

F_HZ = float(os.environ.get("ROOFLINE_CLOCK_HZ", 940e6))  # v5e TensorCore
S = int(os.environ.get("ROOFLINE_SYMBOLS", 10240))
T = int(os.environ.get("ROOFLINE_T", 16))
G = int(os.environ.get("ROOFLINE_GRIDS", 24))
REPEATS = int(os.environ.get("ROOFLINE_REPEATS", 3))


def run_point(cap, block_s, block_t):
    config = BookConfig(cap=cap, max_fills=16, dtype=jnp.int32)
    stepper = jax.jit(
        lambda books, ops: pallas_batch_step(
            config, books, ops, block_s=block_s, block_t=block_t
        ),
        donate_argnums=(0,),
    )
    fold = jax.jit(lambda o: jnp.sum(o.n_fills))
    raw = build_grids(S, T, G + 2, dtype=np.int32)
    for d in raw:
        d["volume"] = (d["volume"] // 1_000_000).astype(np.int32)
    grids = [jax.device_put(DeviceOp(**d)) for d in raw]
    jax.block_until_ready(grids)
    books = init_books(config, S)
    books, outs = stepper(books, grids[0])
    acc = fold(outs)
    books, outs = stepper(books, grids[1])
    int(acc + fold(outs))
    books0 = jax.tree.map(jnp.copy, books)
    int(jnp.sum(books0.count))
    best = float("inf")
    for _ in range(REPEATS):
        books = jax.tree.map(jnp.copy, books0)
        int(jnp.sum(books.count))
        acc = None
        t0 = time.perf_counter()
        for g in grids[2:]:
            books, outs = stepper(books, g)
            f = fold(outs)
            acc = f if acc is None else acc + f
        int(acc)  # completion barrier
        best = min(best, time.perf_counter() - t0)
    rate = S * T * G / best
    # Cycles each serial time step costs one lane block: rate = (S/B_s
    # blocks advance in parallel is FALSE — blocks are grid-parallel in
    # sequence on one core) => time = (S/block_s) * T * C / f;
    # C = f * block_s / rate.
    cycles = F_HZ * block_s / rate
    print(
        json.dumps(
            dict(
                cap=cap,
                block_s=block_s,
                block_t=block_t,
                orders_per_sec=round(rate),
                cycles_per_block_step=round(cycles, 1),
            )
        ),
        flush=True,
    )
    return rate


def analytic_table(dtype="int32"):
    """Per-entry roofline table from the compiled executables'
    cost/memory analysis (gome_tpu.obs.costmodel) — the measured
    arithmetic intensity each entry presents to the memory system, not a
    hand count. An intensity far below the machine balance (~100s of
    flops/byte on TPU) confirms these integer kernels are bandwidth/
    dependency-bound, which is why the sweeps below probe launch and
    blocking overheads rather than FLOP ceilings."""
    from gome_tpu.obs import costmodel

    rows = [r for r in costmodel.entry_report(dtype) if "error" not in r]
    print(f"# analytic roofline ({dtype}, canonical envelope geometry)")
    print(
        "# {:<26} {:>10} {:>12} {:>10} {:>12} {:>10}".format(
            "entry", "flops/ord", "bytes/ord", "flops/byte", "peak_hbm_B",
            "jaxpr_ops",
        )
    )
    for r in rows:
        fmt = lambda v, p=1: "-" if v is None else f"{v:.{p}f}"
        print(
            "# {:<26} {:>10} {:>12} {:>10} {:>12} {:>10}".format(
                r["entry"],
                fmt(r.get("flops_per_order")),
                fmt(r.get("bytes_per_order")),
                fmt(r.get("arithmetic_intensity"), 3),
                str(r.get("peak_hbm_bytes")),
                str(r.get("jaxpr_eqns")),
            )
        )
    for d in costmodel.donation_report(dtype):
        if "error" not in d:
            print(
                f"# donation {d['entry']}: peak "
                f"{d['public_peak_hbm_bytes']} -> "
                f"{d['donating_peak_hbm_bytes']} B "
                f"(saved {d['peak_hbm_saved_bytes']})"
            )


def measured_table(dtype="int32"):
    """The MEASURED roofline joined against the analytic table
    (gome_tpu.obs.profiler): a jax.profiler capture drives the same
    canonical entries the analytic table reports, attributes per-entry
    device time from the trace events, and divides the analytic work by
    it — achieved GFLOP/s, achieved GB/s, and efficiency vs the
    machine's roofline ceiling (min(peak_flops, intensity * peak_bw);
    set GOME_PEAK_GFLOPS / GOME_PEAK_GBPS to override the one-shot
    calibration). Works on any backend the profiler supports, CPU
    included."""
    from gome_tpu.obs.profiler import measured_entry_report

    rep = measured_entry_report(
        dtype, repeats=int(os.environ.get("ROOFLINE_PROFILE_REPEATS", 8))
    )
    pk = rep["peaks"]
    print(
        f"# measured roofline ({dtype}, {rep['platform']}; peaks "
        f"{pk['peak_gflops']} GFLOP/s, {pk['peak_gbps']} GB/s, "
        f"{pk['source']})"
    )
    print(
        "# {:<26} {:>10} {:>12} {:>10} {:>12} {:>8}".format(
            "entry", "dev_us", "ach_GFLOP/s", "ach_GB/s", "ceil_GFLOP/s",
            "eff_%",
        )
    )
    fmt = lambda v, p=3: "-" if v is None else f"{v:.{p}f}"
    for name, r in rep["entries"].items():
        if "error" in r:
            print(f"# {name:<26} error: {r['error']}")
            continue
        print(
            "# {:<26} {:>10} {:>12} {:>10} {:>12} {:>8}".format(
                name,
                fmt(r.get("device_us_per_call")),
                fmt(r.get("achieved_gflops")),
                fmt(r.get("achieved_gbps")),
                fmt(r.get("roofline_ceiling_gflops")),
                fmt(r.get("efficiency_pct"), 4),
            )
        )
    print(f"# perfetto trace: {rep['perfetto_trace']}")


def main():
    dtype = os.environ.get("ROOFLINE_DTYPE", "int32")
    analytic_table(dtype)
    if "--table" in sys.argv:
        return
    if "--measured" in sys.argv:
        measured_table(dtype)
        return
    # Headline point + cap sweep at fixed blocking.
    for cap in (64, 128, 256, 512):
        run_point(cap, 128, min(T, 16))
    # block_t sweep at headline cap.
    for bt in (1, 2, 4, 8, 16):
        if T % bt == 0:
            run_point(256, 128, bt)


if __name__ == "__main__":
    main()
